"""Arena-pooled zero-copy batch assembly (ISSUE 1 tentpole).

Locks two contracts:

1. **Parity** — the arena/deferred builder path produces byte-identical
   batches to the legacy ``stream() + collate`` path across nested
   dicts/tuples, ragged leaves, mixed dtypes, non-contiguous arrays, and
   both wire encodings (raw-buffer multipart and compat pickle), with
   and without a recycled arena, including the precompiled-plan fast
   path AND its generic-walk fallback.
2. **Backpressure** — a slow consumer exhausts the ArenaPool and stalls
   assembly (bounded memory) instead of allocating; recycling resumes it.
"""

import threading
import time

import numpy as np
import pytest

from blendjax import wire
from blendjax.btt.arena import Arena, ArenaBatch, ArenaPool
from blendjax.btt.collate import collate
from blendjax.btt.dataset import RemoteIterableDataset, _BatchBuilder
from helpers.producers import ProducerFleet


def assert_tree_equal(a, b, path=""):
    """Structure + dtype + byte equality over collated pytrees."""
    if isinstance(a, dict):
        assert isinstance(b, dict) and a.keys() == b.keys(), path
        for k in a:
            assert_tree_equal(a[k], b[k], f"{path}/{k}")
    elif isinstance(a, (list, tuple)):
        assert type(a) is type(b) and len(a) == len(b), path
        for i, (x, y) in enumerate(zip(a, b)):
            assert_tree_equal(x, y, f"{path}[{i}]")
    elif isinstance(a, np.ndarray):
        assert isinstance(b, np.ndarray), (path, type(b))
        assert a.dtype == b.dtype and a.shape == b.shape, path
        np.testing.assert_array_equal(a, b, err_msg=path)
    else:
        assert a == b, (path, a, b)


def build_batch(msgs, batch_size=None, arena=None, cache=None):
    b = _BatchBuilder(
        batch_size or len(msgs),
        arena=arena,
        defer=True,
        schema_cache=cache if cache is not None else {},
    )
    for m in msgs:
        b.add_message(m)
    return b.finish()


def legacy_batch(msgs):
    return collate([wire.decode(m) for m in msgs])


class TestArenaParity:
    """Arena path == legacy collate path, byte for byte."""

    @pytest.mark.parametrize("raw", [True, False])
    def test_nested_dicts_tuples_scalars(self, raw):
        def make(i):
            return {
                "image": np.full((8, 6, 3), i, np.uint8),
                "nested": {
                    "xy": np.array([i, i + 1], np.float32),
                    "deep": {"flag": bool(i % 2)},
                    "tag": f"t{i}",
                },
                "tup": (np.arange(3, dtype=np.int32) + i, i * 1.5),
                "pts": [np.full((2, 2), i, np.float64)],
                "frameid": i,
            }

        cache = {}
        for trial in range(2):  # second trial exercises the cached plan
            msgs = [wire.encode(make(i), raw_buffers=raw) for i in range(4)]
            got = build_batch(msgs, cache=cache)
            assert_tree_equal(legacy_batch(msgs), got)

    def test_ragged_and_mixed_dtype_degrade(self):
        msgs = []
        for i in range(3):
            msgs.append(wire.encode({
                "img": np.full((4 + i, 3), i, np.uint8),  # ragged
                "val": np.array([i], np.float32 if i < 2 else np.float64),
                "k": i,
            }, raw_buffers=True))
        got = build_batch(msgs, batch_size=4)  # also a partial batch
        ref = legacy_batch(msgs)
        assert_tree_equal(ref, got)
        assert isinstance(got["img"], list)  # ragged stays a list
        assert got["val"].dtype == np.float64  # upcast rule preserved

    def test_non_contiguous_arrays(self):
        base = np.arange(96, dtype=np.int16).reshape(8, 12)
        msgs = [
            wire.encode(
                {"a": np.asfortranarray(base + i), "b": base[::2, ::3] + i},
                raw_buffers=True,
            )
            for i in range(4)
        ]
        assert_tree_equal(legacy_batch(msgs), build_batch(msgs))

    def test_compat_pickle_messages_fall_back_to_collate_rules(self):
        # single-frame pickles carry materialized ndarrays; the builder
        # must match collate exactly for them too (on-by-default path
        # keeps every existing *.blend.py producer working unmodified)
        msgs = [
            wire.encode(
                {"image": np.full((5, 4), i, np.uint8), "frameid": i},
                raw_buffers=False,
            )
            for i in range(4)
        ]
        assert len(msgs[0]) == 1  # really the compat encoding
        assert_tree_equal(legacy_batch(msgs), build_batch(msgs))

    def test_key_semantics_and_plan_fallback(self):
        img = np.zeros((4, 4), np.uint8)
        cache = {}
        # batch 1 fixes the schema/plan
        msgs = [
            wire.encode({"image": img, "frameid": i}, raw_buffers=True)
            for i in range(2)
        ]
        build_batch(msgs, cache=cache)
        # batch 2: an extra key appears -> plan fallback, key adopted
        # (legacy collate keys each batch off its first item)
        msgs2 = [
            wire.encode(
                {"image": img, "frameid": i, "extra": i}, raw_buffers=True
            )
            for i in range(2)
        ]
        got = build_batch(msgs2, cache=cache)
        assert_tree_equal(legacy_batch(msgs2), got)
        assert "extra" in got
        # batch 3: a late-message-only key is dropped, missing key raises
        msgs3 = [
            wire.encode({"image": img, "frameid": 0}, raw_buffers=True),
            wire.encode(
                {"image": img, "frameid": 1, "late": 9}, raw_buffers=True
            ),
        ]
        got3 = build_batch(msgs3, cache=cache)
        assert "late" not in got3
        with pytest.raises(KeyError):
            build_batch([
                wire.encode({"image": img, "frameid": 0}, raw_buffers=True),
                wire.encode({"image": img}, raw_buffers=True),
            ], cache=cache)

    def test_eager_drift_degrade_does_not_alias_recycled_arena(self):
        """Eager (shm-style) assembly: a mid-batch shape drift degrades a
        key to a ragged list; the already-scattered slots must be COPIES,
        not views into the arena buffer a later batch will overwrite."""
        pool = ArenaPool(1)
        arena = pool.acquire()
        b1 = _BatchBuilder(2, arena=arena)
        b1.add_message(wire.encode({"x": np.array([0, 1, 2, 3])},
                                   raw_buffers=True))
        b1.add_message(wire.encode({"x": np.array([9, 9])},
                                   raw_buffers=True))  # drift -> ragged
        batch1 = b1.finish()
        arena.release()
        arena2 = pool.acquire()  # same arena, recycled
        b2 = _BatchBuilder(2, arena=arena2)
        for _ in range(2):
            b2.add_message(wire.encode({"x": np.array([-1, -1, -1, -1])},
                                       raw_buffers=True))
        b2.finish()
        np.testing.assert_array_equal(batch1["x"][0], [0, 1, 2, 3])

    def test_arena_buffers_are_recycled_not_reallocated(self):
        pool = ArenaPool(2)
        cache = {}
        arena = pool.acquire()
        msgs = [
            wire.encode(
                {"image": np.full((16, 16), i, np.uint8)}, raw_buffers=True
            )
            for i in range(4)
        ]
        first = build_batch(msgs, arena=arena, cache=cache)
        buf_id = id(first["image"])
        arena.release()
        arena2 = pool.acquire()
        assert arena2 is arena  # freelist reuse
        msgs2 = [
            wire.encode(
                {"image": np.full((16, 16), 40 + i, np.uint8)},
                raw_buffers=True,
            )
            for i in range(4)
        ]
        second = build_batch(msgs2, arena=arena2, cache=cache)
        # same backing buffer, new bytes — zero per-batch allocation
        assert id(second["image"]) == buf_id
        assert_tree_equal(legacy_batch(msgs2), second)


class TestArenaPoolBackpressure:
    def test_exhaustion_blocks_then_recycle_unblocks(self):
        pool = ArenaPool(2)
        a1, a2 = pool.acquire(), pool.acquire()
        assert pool.in_use == 2
        t0 = time.monotonic()
        assert pool.acquire(timeout=0.2) is None  # exhausted: blocks
        assert time.monotonic() - t0 >= 0.2
        got = []
        waiter = threading.Thread(
            target=lambda: got.append(pool.acquire(timeout=5.0)), daemon=True
        )
        waiter.start()
        time.sleep(0.05)
        a1.release()  # consumer finally recycles
        waiter.join(timeout=5)
        assert got and got[0] is a1
        a2.release()
        assert pool.in_use == 1  # got[0] still checked out

    def test_stop_event_aborts_wait(self):
        pool = ArenaPool(1)
        pool.acquire()
        stop = threading.Event()
        res = {}

        def wait():
            res["a"] = pool.acquire(stop_event=stop)

        t = threading.Thread(target=wait, daemon=True)
        t.start()
        time.sleep(0.05)
        stop.set()
        t.join(timeout=5)
        assert res["a"] is None

    def test_double_recycle_is_idempotent(self):
        pool = ArenaPool(1)
        arena = pool.acquire()
        batch = ArenaBatch({"x": np.zeros(2)}, arena)
        batch.recycle()
        batch.recycle()
        assert pool.in_use == 0
        assert pool.acquire() is arena

    def test_stream_backpressures_into_pool(self):
        """End to end over real sockets: a consumer that never recycles
        stalls the stream once the pool drains; recycling resumes it."""
        pool = ArenaPool(2)
        with ProducerFleet(num_producers=1, raw_buffers=True) as fleet:
            ds = RemoteIterableDataset(
                fleet.addresses, max_items=64, timeoutms=20000
            )
            gen = ds.stream_batches(4, arena_pool=pool)
            held = [next(gen), next(gen)]  # exhausts the pool
            assert all(isinstance(b, ArenaBatch) for b in held)
            assert pool.in_use == 2
            blocked = []
            t = threading.Thread(
                target=lambda: blocked.append(next(gen)), daemon=True
            )
            t.start()
            time.sleep(0.5)
            assert not blocked, "stream must stall while the pool is dry"
            held[0].recycle()  # transfer "completes"
            t.join(timeout=10)
            assert len(blocked) == 1
            assert_is_batch(blocked[0])
            gen.close()

    def test_generator_close_does_not_double_release_yielded_arena(self):
        """Closing the stream generator right at the yield must NOT
        return the just-yielded batch's arena to the pool — the consumer
        still owns it until recycle()."""
        pool = ArenaPool(2)
        with ProducerFleet(num_producers=1, raw_buffers=True) as fleet:
            ds = RemoteIterableDataset(
                fleet.addresses, max_items=64, timeoutms=20000
            )
            gen = ds.stream_batches(4, arena_pool=pool)
            batch = next(gen)
            gen.close()  # GeneratorExit lands at the suspended yield
        assert isinstance(batch, ArenaBatch)
        assert pool.in_use == 1  # still owned by the yielded batch
        # the arena must not have been handed to anyone else meanwhile
        fresh = pool.acquire(timeout=1.0)
        assert fresh is not batch.arena
        batch.recycle()
        assert pool.in_use == 1  # only `fresh` remains out

    def test_shm_stream_yields_arena_batches(self):
        """The native shm transport threads the same pool through its
        eager (record-lifetime-bounded) builder."""
        import os
        import uuid

        from blendjax.btb.publisher import DataPublisher
        from blendjax.native import native_available

        if not native_available():
            pytest.skip("native ring unavailable")
        addr = f"shm://bjx-test-arena-{os.getpid()}-{uuid.uuid4().hex[:6]}"

        def produce():
            pub = DataPublisher(addr, btid=0, raw_buffers=True,
                                sndtimeoms=500)
            i = 0
            while i < 8:
                if pub.publish(image=np.full((8, 8), i, np.uint8),
                               frameid=i):
                    i += 1
            pub.close()

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        pool = ArenaPool(3)
        ds = RemoteIterableDataset([addr], max_items=8, timeoutms=10000)
        batches = []
        for b in ds.stream_batches(4, arena_pool=pool):
            assert isinstance(b, ArenaBatch)
            batches.append(b.data["frameid"].tolist())
            b.recycle()
        t.join(timeout=10)
        assert batches == [[0, 1, 2, 3], [4, 5, 6, 7]]
        assert pool.in_use == 0

    def test_gather_into_matches_numpy(self):
        from blendjax.native.ring import gather_into

        rng = np.random.default_rng(0)
        parts = [rng.integers(0, 255, (40, 7), np.uint8) for _ in range(6)]
        dst = np.empty((6, 40, 7), np.uint8)
        gather_into(dst, parts)
        np.testing.assert_array_equal(dst, np.stack(parts))
        # buffer-protocol sources (the wire-frame case) and fortran order
        dst2 = np.empty((3, 4, 4), np.float32)
        srcs = [
            np.arange(16, dtype=np.float32).reshape(4, 4) + i for i in range(3)
        ]
        gather_into(
            dst2,
            [memoryview(srcs[0].tobytes()), srcs[1], np.asfortranarray(srcs[2])],
        )
        np.testing.assert_array_equal(dst2, np.stack(srcs))
        with pytest.raises(ValueError, match="bytes"):
            gather_into(np.empty(3, np.uint8), [b"toolongbytes"])


def assert_is_batch(b):
    data = b.data if isinstance(b, ArenaBatch) else b
    assert isinstance(data, dict) and "image" in data


class TestFeedBoundBench:
    def test_measure_reports_both_paths_and_stages(self):
        from benchmarks.feed_bound import measure

        out = measure(width=32, height=24, batch=4, seconds=0.4, nmsgs=8,
                      telemetry_seconds=0.8)
        limits = out["feed_limit_batches_per_sec"]
        assert limits["legacy"] > 0 and limits["arena"] > 0
        assert out["arena_over_legacy"] is not None
        assert {"arena_wait", "scatter", "recycle"} <= set(out["stages"])
        # the telemetry-plane sanity ratio rides along (short budget
        # here: structure only, the real floor is benched at 3.2 s)
        assert out["telemetry_overhead_x"] > 0
        assert out["telemetry"]["enabled_windows"]["n"] >= 4

    def test_bench_assemble_carries_feed_bound(self):
        import bench

        fb = {
            "feed_limit_batches_per_sec": {"legacy": 100.0, "arena": 140.0},
            "arena_over_legacy": 1.4,
            "stages": {"scatter": {"count": 1, "total_s": 0.1,
                                   "mean_ms": 100.0}},
        }
        out = bench.assemble({}, host_fallback=lambda: 1.0, feed_bound=fb)
        assert out["feed_bound"] is fb
        assert out["feed_bound"]["feed_limit_batches_per_sec"]["arena"] == 140.0
        line = bench.headline(out)
        assert line["feed_arena_x"] == 1.4
        import json

        assert len(json.dumps(line)) + 1 <= bench.HEADLINE_BYTE_BUDGET
