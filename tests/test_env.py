"""Env/RL layer tests (reference coverage: ``tests/test_env.py:12-46`` —
full RPC loop determinism incl. reset-after-done and bookkeeping; blendjax
adds scripted-agent unit tests of BaseEnv ordering and EnvPool coverage)."""

import numpy as np
import pytest

from blendjax.btt.env import kwargs_to_cli, launch_env
from blendjax.btt.envpool import EnvPool, launch_env_pool
from helpers import BLEND_SCRIPTS, FAKE_BLENDER, fake_bpy

ENV_SCRIPT = f"{BLEND_SCRIPTS}/env.blend.py"


@pytest.fixture
def fake_blender(monkeypatch):
    monkeypatch.setenv("BLENDJAX_BLENDER", FAKE_BLENDER)


def test_kwargs_to_cli():
    assert kwargs_to_cli({"render_every": 3, "real_time": True, "debug": False}) == [
        "--render-every", "3", "--real-time", "--no-debug",
    ]


def test_base_env_scripted_agent_ordering():
    bpy = fake_bpy.install()
    import sys

    sys.modules.pop("blendjax.btb.env", None)
    from blendjax.btb.env import BaseEnv

    calls = []

    class Env(BaseEnv):
        def __init__(self, agent):
            super().__init__(agent)
            self.value = 0.0

        def _env_reset(self):
            calls.append("reset")
            self.value = 0.0

        def _env_prepare_step(self, action):
            calls.append(f"prepare_{action}")
            self.value = action

        def _env_post_step(self):
            calls.append(f"post_{self.value}")
            return {"obs": self.value, "reward": self.value}

    actions = iter([10, 20, 30])
    seen = []

    def agent(env, **ctx):
        seen.append((ctx["time"], ctx["obs"], ctx["done"]))
        return BaseEnv.CMD_STEP, next(actions)

    env = Env(agent)
    env.run(frame_range=(1, 4), use_animation=True)
    bpy.pump_draw()  # post of frame 1
    for _ in range(3):
        bpy.pump_frame()
    env.events.stop()

    # reset once; agent first consulted at frame 2 with frame-1 obs; each
    # action applied before that frame's post step
    assert calls == [
        "reset", "post_0.0",
        "prepare_10", "post_10",
        "prepare_20", "post_20",
        "prepare_30", "post_30",
    ]
    assert seen[0] == (2, 0.0, False)
    assert seen[1] == (3, 10, False)
    # at frame 4 the done horizon (frame_range[1]=4) is already reached
    assert seen[2] == (4, 20, True)


def test_remote_env_rpc_loop(fake_blender):
    with launch_env(
        scene="", script=ENV_SCRIPT, background=True, horizon=5, timeoutms=30000
    ) as env:
        obs, info = env.reset()
        assert obs == 0.0
        assert info["time"] == 2  # reset reply carries frame-2 context

        obs, reward, done, info = env.step(4.0)
        assert obs == 4.0 and reward == pytest.approx(0.4) and not done
        t0 = info["time"]
        obs, reward, done, info = env.step(8.0)
        assert obs == 8.0 and reward == pytest.approx(0.8)
        assert info["time"] == t0 + 1  # one step == one frame

        # run to the horizon -> done
        while not done:
            obs, reward, done, info = env.step(1.0)
        assert info["time"] >= 5

        # reset after done restarts the episode
        obs, info = env.reset()
        assert obs == 0.0
        obs, reward, done, _ = env.step(2.0)
        assert obs == 2.0 and not done


def test_env_pool_batched(fake_blender):
    with launch_env_pool(
        scene="",
        script=ENV_SCRIPT,
        num_instances=2,
        background=True,
        horizon=6,
        timeoutms=30000,
    ) as pool:
        obs, infos = pool.reset()
        np.testing.assert_allclose(obs, [0.0, 0.0])
        assert len(infos) == 2

        obs, rewards, dones, infos = pool.step([1.0, 3.0])
        np.testing.assert_allclose(obs, [1.0, 3.0])
        np.testing.assert_allclose(rewards, [0.1, 0.3])
        assert not dones.any()

        # drive both to done
        for _ in range(8):
            obs, rewards, dones, infos = pool.step([1.0, 1.0])
            if dones.any():
                break
        assert dones.all()  # same horizon -> finish together

        # autoreset: next step resets them, fresh obs, zero reward
        obs, rewards, dones, infos = pool.step([9.0, 9.0])
        np.testing.assert_allclose(obs, [0.0, 0.0])
        np.testing.assert_allclose(rewards, [0.0, 0.0])
        assert not dones.any()
        # and stepping continues normally
        obs, rewards, dones, infos = pool.step([5.0, 6.0])
        np.testing.assert_allclose(obs, [5.0, 6.0])


def test_pool_action_count_mismatch(fake_blender):
    pool = EnvPool.__new__(EnvPool)
    pool.num_envs = 2
    pool.autoreset = False
    pool._needs_reset = np.zeros(2, bool)
    with pytest.raises(ValueError, match="expected 2 actions"):
        pool.step([1.0])


def test_remote_controlled_agent_real_time_nonblocking():
    """real_time=True: with no pending request the agent must not block the
    frame loop (returns CMD_STEP, None); requests are served when present
    (reference behavior ``btb/env.py:220-233,251-252``)."""
    import types

    import zmq

    from blendjax import wire
    from blendjax.btb.env import BaseEnv, RemoteControlledAgent
    from helpers.producers import free_port

    addr = f"tcp://127.0.0.1:{free_port()}"
    agent = RemoteControlledAgent(addr, real_time=True, timeoutms=2000)
    ctx = zmq.Context.instance()
    req = ctx.socket(zmq.REQ)
    req.setsockopt(zmq.LINGER, 0)
    req.setsockopt(zmq.RCVTIMEO, 5000)
    req.connect(addr)
    env = types.SimpleNamespace(state=BaseEnv.STATE_RUN)
    try:
        # no request pending -> simulation continues without action
        assert agent(env, obs=0.0, done=False) == (BaseEnv.CMD_STEP, None)
        assert agent(env, obs=0.0, done=False) == (BaseEnv.CMD_STEP, None)

        # a pending step request is consumed
        wire.send_message(req, {"cmd": "step", "action": 3.5})
        import time

        time.sleep(0.2)  # let the request arrive
        cmd, action = agent(env, obs=0.0, done=False)
        assert cmd == BaseEnv.CMD_STEP and action == 3.5

        # next frame: the reply (previous ctx) goes out even in real time
        cmd, action = agent(env, obs=3.5, reward=1.0, done=False, time=7)
        assert (cmd, action) == (BaseEnv.CMD_STEP, None)
        reply = wire.recv_message(req)
        assert reply["obs"] == 3.5 and reply["time"] == 7

        # reset request while running -> CMD_RESTART
        wire.send_message(req, {"cmd": "reset"})
        time.sleep(0.2)
        cmd, action = agent(env, obs=3.5, done=False)
        assert cmd == BaseEnv.CMD_RESTART and action is None
    finally:
        agent.close()
        req.close(0)


def test_adapt_step_result_both_apis():
    from blendjax.btt.env import adapt_step_result

    # gymnasium: 5-tuple with terminated/truncated split
    out = adapt_step_result(1.0, 0.5, 1, {"k": 2}, gymnasium_api=True)
    assert out == (1.0, 0.5, True, False, {"k": 2})
    assert isinstance(out[2], bool)
    # classic gym: legacy 4-tuple, done passed through
    assert adapt_step_result(1.0, 0.5, True, {}, gymnasium_api=False) == (
        1.0, 0.5, True, {},
    )


def test_gymnasium_adapter_api(fake_blender):
    """Under gymnasium the adapter must satisfy the gymnasium.Env contract:
    reset() -> (obs, info), step() -> 5-tuple — VERDICT r01 #4 (reference
    gym-correctness: ``/root/reference/pkg_pytorch/blendtorch/btt/env.py:195-313``)."""
    gymnasium = pytest.importorskip("gymnasium")
    from blendjax.btt.env import OpenAIRemoteEnv, USING_GYMNASIUM

    assert USING_GYMNASIUM

    class _TestEnv(OpenAIRemoteEnv):
        def __init__(self):
            super().__init__()
            self.launch(
                scene="", script=ENV_SCRIPT, background=True, horizon=5
            )
            self.action_space = gymnasium.spaces.Box(
                -100.0, 100.0, shape=(), dtype=np.float32
            )
            self.observation_space = gymnasium.spaces.Box(
                -100.0, 100.0, shape=(), dtype=np.float32
            )

    env_id = "blendjax-testenv-v0"
    if env_id not in gymnasium.registry:
        gymnasium.register(id=env_id, entry_point=_TestEnv)
    env = gymnasium.make(env_id, disable_env_checker=False)
    try:
        result = env.reset(seed=123)
        assert isinstance(result, tuple) and len(result) == 2
        obs, info = result
        assert isinstance(info, dict)

        result = env.step(4.0)
        assert len(result) == 5
        obs, reward, terminated, truncated, info = result
        assert obs == 4.0 and reward == pytest.approx(0.4)
        assert terminated is False and truncated is False

        terminated = False
        while not terminated:
            obs, reward, terminated, truncated, info = env.step(1.0)
        # reset after termination works and returns the 2-tuple again
        obs, info = env.reset()
        assert isinstance(info, dict)
    finally:
        env.close()


def test_vector_env_gymnasium_contract(fake_blender):
    """BlenderVectorEnv follows the gymnasium VectorEnv API over a real
    (fake-Blender) fleet: batched spaces, 5-tuple step, NEXT_STEP
    autoreset semantics matching EnvPool's native behavior."""
    import gymnasium

    from blendjax.btt.vector_env import launch_vector_env

    obs_space = gymnasium.spaces.Box(-np.inf, np.inf, shape=(), dtype=np.float64)
    act_space = gymnasium.spaces.Box(-10.0, 10.0, shape=(), dtype=np.float64)
    with launch_vector_env(
        scene="",
        script=ENV_SCRIPT,
        num_instances=2,
        single_observation_space=obs_space,
        single_action_space=act_space,
        background=True,
        horizon=4,
        timeoutms=30000,
    ) as env:
        assert env.num_envs == 2
        assert env.observation_space.shape == (2,)
        assert env.action_space.shape == (2,)

        obs, info = env.reset()
        assert obs.shape == (2,)
        np.testing.assert_allclose(obs, [0.0, 0.0])
        assert "env_infos" in info

        obs, rew, term, trunc, info = env.step(np.array([1.0, 3.0]))
        np.testing.assert_allclose(obs, [1.0, 3.0])
        np.testing.assert_allclose(rew, [0.1, 0.3])
        assert term.dtype == bool and trunc.dtype == bool
        assert not term.any() and not trunc.any()

        # run to termination; NEXT_STEP autoreset: the step AFTER
        # termination returns the reset observation with zero reward
        for _ in range(6):
            obs, rew, term, trunc, info = env.step(np.array([2.0, 2.0]))
            if term.any():
                break
        assert term.all()
        obs, rew, term, trunc, info = env.step(np.array([7.0, 7.0]))
        np.testing.assert_allclose(obs, [0.0, 0.0])
        np.testing.assert_allclose(rew, [0.0, 0.0])
        assert not term.any()
