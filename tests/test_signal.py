from blendjax.btb.signal import Signal


def test_invoke_order_and_args():
    calls = []
    sig = Signal()
    sig.add(lambda x: calls.append(("a", x)))
    sig.add(lambda tag, x: calls.append((tag, x)), "bound")
    sig.invoke(7)
    assert calls == [("a", 7), ("bound", 7)]


def test_remove_by_handle():
    sig = Signal()
    h = sig.add(lambda: None)
    assert len(sig) == 1
    sig.remove(h)
    assert len(sig) == 0


def test_handler_can_unregister_during_dispatch():
    sig = Signal()
    calls = []

    def once():
        calls.append(1)
        sig.remove(h)

    h = sig.add(once)
    sig.add(lambda: calls.append(2))
    sig.invoke()
    sig.invoke()
    assert calls == [1, 2, 2]
