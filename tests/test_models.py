"""Model-zoo tests: shapes, jit/grad viability, loss descent on synthetic
data, score-function estimator direction, and policy math."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from blendjax.models import detector, discriminator, policy, probmodel
from blendjax.models.train import TrainState, make_train_step


def test_detector_shapes_and_dtype():
    params = detector.init(jax.random.PRNGKey(0), num_keypoints=8)
    x = jnp.zeros((2, 64, 64, 3), jnp.float32)
    out = jax.jit(detector.apply)(params, x)
    assert out.shape == (2, 8, 2)
    assert out.dtype == jnp.float32  # head re-cast for stable sigmoid
    assert ((np.asarray(out) >= 0) & (np.asarray(out) <= 1)).all()


def test_detector_learns_constant_target():
    key = jax.random.PRNGKey(1)
    params = detector.init(key, num_keypoints=2, channels=(8, 16), hidden=32)
    batch = {
        "image": jax.random.uniform(key, (8, 32, 32, 3)),
        "xy": jnp.tile(jnp.array([[[0.25, 0.75], [0.5, 0.5]]]), (8, 1, 1)),
    }
    step = make_train_step(detector.loss_fn, optax.adam(3e-3))
    state = TrainState.create(params, optax.adam(3e-3))
    first = None
    for _ in range(60):
        state, loss = step(state, batch)
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.5, (first, float(loss))


def test_discriminator_separates():
    key = jax.random.PRNGKey(2)
    params = discriminator.init(key, in_channels=1, widths=(8, 16))
    real = jnp.ones((8, 32, 32, 1)) * 0.9
    fake = jnp.zeros((8, 32, 32, 1))
    opt = optax.adam(1e-2)
    step = make_train_step(
        lambda p, b: discriminator.d_loss_fn(p, b["real"], b["fake"]), opt
    )
    state = TrainState.create(params, opt)
    for _ in range(40):
        state, loss = step(state, {"real": real, "fake": fake})
    lr = discriminator.apply(state.params, real)
    lf = discriminator.apply(state.params, fake)
    assert float(lr.mean()) > float(lf.mean())
    # per-sample scores positive and finite
    s = discriminator.sim_scores(state.params, fake)
    assert s.shape == (8,) and bool(jnp.isfinite(s).all())


def test_probmodel_score_gradient_direction():
    """If larger samples get lower loss, the estimator must push mu up."""
    params = probmodel.init(mu=[0.0], sigma=[0.5])
    key = jax.random.PRNGKey(3)
    samples = probmodel.sample(params, key, 512)
    losses = -jnp.log(samples[:, 0])  # loss decreases with sample value
    grads = jax.grad(probmodel.score_loss)(params, samples, losses, baseline=losses.mean())
    assert float(grads["mu"][0]) < 0  # gradient descent increases mu
    # log_prob agrees with scipy-style closed form at the median
    lp = probmodel.log_prob(params, jnp.array([[1.0]]))  # x=1 -> log x = mu
    expected = -jnp.log(0.5) - 0.5 * jnp.log(2 * jnp.pi)
    np.testing.assert_allclose(float(lp[0]), float(expected), atol=1e-5)
    assert probmodel.mean(params).shape == (1,)


def test_policy_categorical():
    params = policy.init(jax.random.PRNGKey(4), obs_dim=3, num_actions=2)
    obs = jnp.zeros((5, 3))
    actions, logp = policy.sample_action(params, jax.random.PRNGKey(0), obs)
    assert actions.shape == (5,) and logp.shape == (5,)
    lp = policy.categorical_log_prob(params, obs, actions)
    np.testing.assert_allclose(np.asarray(lp), np.asarray(logp), atol=1e-6)
    # log-probs normalize
    all_lp = jax.nn.log_softmax(policy.logits(params, obs))
    np.testing.assert_allclose(np.asarray(jnp.exp(all_lp).sum(-1)), 1.0, atol=1e-6)


def test_discounted_returns_resets_at_done():
    rewards = jnp.ones((4, 1))
    dones = jnp.array([[0.0], [1.0], [0.0], [0.0]])
    ret = policy.discounted_returns(rewards, dones, gamma=0.5)
    # t=3: 1; t=2: 1+0.5 = 1.5; t=1: done -> 1; t=0: 1 + 0.5*1 = 1.5
    np.testing.assert_allclose(np.asarray(ret[:, 0]), [1.5, 1.0, 1.5, 1.0])


def test_reinforce_loss_gradient_sanity():
    params = policy.init(jax.random.PRNGKey(5), obs_dim=2, num_actions=2)
    obs = jax.random.normal(jax.random.PRNGKey(6), (16, 2))
    actions = jnp.zeros(16, jnp.int32)
    returns = jnp.linspace(0.0, 1.0, 16)
    g = jax.grad(policy.reinforce_loss)(params, obs, actions, returns)
    flat, _ = jax.flatten_util.ravel_pytree(g)
    assert bool(jnp.isfinite(flat).all()) and float(jnp.abs(flat).max()) > 0


def test_gae_matches_manual_recursion():
    """GAE against a hand-rolled reference on a rollout with an episode
    boundary (the mask must cut both bootstrap and trace)."""
    T, N = 5, 2
    rng = np.random.default_rng(0)
    rewards = jnp.asarray(rng.random((T, N)), jnp.float32)
    values = jnp.asarray(rng.random((T, N)), jnp.float32)
    last_values = jnp.asarray(rng.random(N), jnp.float32)
    dones = jnp.zeros((T, N))
    dones = dones.at[2, 0].set(1.0)
    gamma, lam = 0.9, 0.8

    adv, targets = policy.gae(rewards, values, last_values, dones,
                              gamma, lam)

    r, v, d = (np.asarray(x) for x in (rewards, values, dones))
    nv = np.concatenate([v[1:], np.asarray(last_values)[None]], 0)
    want = np.zeros((T, N))
    carry = np.zeros(N)
    for t in reversed(range(T)):
        mask = 1.0 - d[t]
        delta = r[t] + gamma * nv[t] * mask - v[t]
        carry = delta + gamma * lam * mask * carry
        want[t] = carry
    np.testing.assert_allclose(np.asarray(adv), want, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(targets), want + v, rtol=1e-6)


def test_ppo_loss_clips_ratio_and_masks():
    """Non-constant advantages + a hugely-off-policy logp: the clipped
    surrogate must equal clip(ratio) * normalized_adv exactly (analytic
    check — deleting the clip would change the value by orders of
    magnitude), and a zero mask entry must drop its transition from
    every term."""
    actor = policy.init(jax.random.PRNGKey(0), 3, 2)
    critic = policy.value_init(jax.random.PRNGKey(1), 3)
    obs = jax.random.normal(jax.random.PRNGKey(2), (4, 3))
    actions = jnp.zeros((4,), jnp.int32)
    adv = jnp.asarray([2.0, -1.0, 1.0, -2.0])
    logp_now = policy.categorical_log_prob(actor, obs, actions)
    batch = dict(
        obs=obs, actions=actions,
        logp_old=logp_now - 5.0,  # ratio e^5 >> 1+eps everywhere
        advantages=adv,
        targets=policy.value_apply(critic, obs),
    )
    loss = policy.ppo_loss(actor, critic, batch, clip_eps=0.2,
                           vf_coef=0.0, ent_coef=0.0)
    adv_n = (adv - adv.mean()) / (adv.std() + 1e-6)
    ratio = float(jnp.exp(5.0))
    want = -float(jnp.mean(jnp.minimum(
        ratio * adv_n, jnp.clip(ratio, 0.8, 1.2) * adv_n
    )))
    np.testing.assert_allclose(float(loss), want, rtol=1e-4)
    unclipped = -float(jnp.mean(ratio * adv_n))
    assert abs(want - unclipped) > 1.0  # the clip genuinely binds

    # masking: zeroing one lane changes the weighted normalization and
    # drops its surrogate term — equal to recomputing on the kept lanes
    batch["mask"] = jnp.asarray([1.0, 1.0, 1.0, 0.0])
    masked = policy.ppo_loss(actor, critic, batch, clip_eps=0.2,
                             vf_coef=0.0, ent_coef=0.0)
    kept = {k: (v[:3] if k != "obs" else v[:3]) for k, v in batch.items()
            if k != "mask"}
    want_kept = policy.ppo_loss(actor, critic, kept, clip_eps=0.2,
                                vf_coef=0.0, ent_coef=0.0)
    np.testing.assert_allclose(float(masked), float(want_kept), rtol=1e-5)


def test_ppo_loss_continuous_gaussian_path():
    """The continuous-action PPO path (Gaussian logp + closed-form
    entropy): loss is finite, differentiable, and the log_std head
    receives gradient."""
    from blendjax.models import policy

    actor = policy.init(jax.random.PRNGKey(0), 3, 2, continuous=True)
    critic = policy.value_init(jax.random.PRNGKey(1), 3)
    obs = jax.random.normal(jax.random.PRNGKey(2), (6, 3))
    actions, logp = policy.sample_action(
        actor, jax.random.PRNGKey(3), obs
    )
    batch = dict(
        obs=obs, actions=actions, logp_old=logp,
        advantages=jnp.asarray([1.0, -1.0, 0.5, -0.5, 2.0, -2.0]),
        targets=jnp.zeros((6,)),
    )
    loss, grads = jax.value_and_grad(lambda a: policy.ppo_loss(
        a, critic, batch, continuous=True
    ))(actor)
    assert np.isfinite(float(loss))
    assert float(jnp.abs(grads["log_std"]).sum()) > 0
