"""FaultPolicy unit tests: deterministic backoff, retry/deadline/circuit
semantics, and the EventCounters observability surface — all pure-host,
no sockets (the wire-level paths are covered by tests/test_chaos.py)."""

import threading

import pytest

from blendjax.btt.faults import CircuitOpenError, FaultPolicy
from blendjax.utils.timing import FLEET_EVENTS, EventCounters


def test_backoff_deterministic_and_capped():
    policy = FaultPolicy(backoff_base=0.1, backoff_factor=2.0, backoff_max=0.5,
                         jitter=0.25, seed=42)
    a = policy.new_state(key=3)
    b = policy.new_state(key=3)
    seq_a = [a.backoff(n) for n in range(1, 8)]
    seq_b = [b.backoff(n) for n in range(1, 8)]
    assert seq_a == seq_b  # same (seed, key) -> identical jitter stream
    other = policy.new_state(key=4)
    assert [other.backoff(n) for n in range(1, 8)] != seq_a
    # exponential under the cap, jitter-bounded throughout
    for n, d in enumerate(seq_a, start=1):
        base = min(0.5, 0.1 * 2.0 ** (n - 1))
        assert base * 0.75 <= d <= base * 1.25


def test_no_jitter_is_exact():
    policy = FaultPolicy(backoff_base=0.1, backoff_factor=2.0, backoff_max=1.0,
                         jitter=0.0)
    st = policy.new_state()
    assert [st.backoff(n) for n in (1, 2, 3, 4, 5)] == pytest.approx(
        [0.1, 0.2, 0.4, 0.8, 1.0]
    )


def test_run_retries_then_succeeds():
    counters = EventCounters()
    policy = FaultPolicy(max_retries=3, backoff_base=0.01, jitter=0.0)
    calls = []
    slept = []

    def fn(attempt):
        calls.append(attempt)
        if attempt < 2:
            raise TimeoutError("transient")
        return "ok"

    assert policy.run(fn, counters=counters, sleep=slept.append) == "ok"
    assert calls == [0, 1, 2]
    assert counters.get("retries") == 2
    assert counters.get("timeouts") == 2
    assert counters.get("failures") == 0
    assert slept == pytest.approx([0.01, 0.02])


def test_run_exhausts_and_raises():
    counters = EventCounters()
    policy = FaultPolicy(max_retries=2, backoff_base=0.001, jitter=0.0)

    def fn(attempt):
        raise TimeoutError("down")

    with pytest.raises(TimeoutError, match="down"):
        policy.run(fn, counters=counters, sleep=lambda s: None)
    assert counters.get("retries") == 2
    assert counters.get("failures") == 1
    assert counters.get("timeouts") == 3


def test_run_non_retryable_propagates_immediately():
    policy = FaultPolicy(max_retries=5)
    calls = []

    def fn(attempt):
        calls.append(attempt)
        raise ValueError("logic bug, not a fault")

    with pytest.raises(ValueError):
        policy.run(fn, sleep=lambda s: None, counters=EventCounters())
    assert calls == [0]


def test_deadline_stops_retrying():
    # fake clock: every read advances 1.0s, so the post-failure budget
    # check lands exactly on the deadline after the first attempt and
    # only one attempt runs despite max_retries=10
    t = [0.0]

    def clock():
        t[0] += 1.0
        return t[0]

    counters = EventCounters()
    policy = FaultPolicy(max_retries=10, deadline_s=1.0, backoff_base=0.01,
                         jitter=0.0, _clock=clock)
    calls = []

    def fn(attempt):
        calls.append(attempt)
        raise TimeoutError("slow")

    with pytest.raises(TimeoutError):
        policy.run(fn, counters=counters, sleep=lambda s: None)
    assert len(calls) == 1
    assert counters.get("failures") == 1


def test_circuit_opens_and_cools_down():
    t = [0.0]
    policy = FaultPolicy(
        max_retries=0, circuit_threshold=3, circuit_cooldown_s=10.0,
        backoff_base=0.0, jitter=0.0, _clock=lambda: t[0],
    )
    counters = EventCounters()
    state = policy.new_state()

    def fn(attempt):
        raise TimeoutError("dead")

    # three consecutive failures trip the breaker
    for _ in range(3):
        with pytest.raises(TimeoutError):
            policy.run(fn, state=state, counters=counters,
                       sleep=lambda s: None)
    assert counters.get("circuit_opens") == 1
    assert state.circuit_open()

    # while open: rejected without calling fn
    calls = []
    with pytest.raises(CircuitOpenError):
        policy.run(lambda a: calls.append(a), state=state, counters=counters)
    assert calls == []
    assert counters.get("circuit_rejections") == 1

    # after the cooldown: half-open, one trial allowed; success closes it
    t[0] = 11.0
    assert policy.run(lambda a: "back", state=state, counters=counters) == "back"
    assert not state.circuit_open()
    assert state.consecutive_failures == 0


def test_event_counters_thread_safe_and_snapshot():
    c = EventCounters()
    threads = [
        threading.Thread(target=lambda: [c.incr("x") for _ in range(1000)])
        for _ in range(8)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert c.get("x") == 8000
    c.incr("y", 5)
    snap = c.snapshot()
    assert snap == {"x": 8000, "y": 5}
    snap["x"] = 0  # snapshot is a copy
    assert c.get("x") == 8000
    c.reset()
    assert c.snapshot() == {}
    assert c.get("missing") == 0


def test_fleet_events_vocabulary_is_reported_zero_filled():
    """health() zero-fills from FLEET_EVENTS; lock the core names."""
    for name in ("deaths", "restarts", "retries", "timeouts", "quarantines",
                 "readmissions", "circuit_opens", "transfer_gate_backstops"):
        assert name in FLEET_EVENTS
