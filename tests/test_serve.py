"""Policy-serving inference tier tests (docs/serving.md).

The load-bearing ones are the parity locks: per-row-position batched
``decode_step`` must equal per-episode serial decode at heterogeneous
timesteps (with and without ``window`` ring caches) — one batched
compute serving many episodes is a scheduling choice, not a numerics
choice — and the exactly-once chaos tests: every submitted request
yields exactly one applied decode however the wire mangles it, and a
SIGKILL'd server respawned by ``FleetWatchdog`` lets clients resume
after ``reset()``.
"""

import functools
import json
import threading
import time

import numpy as np
import pytest

from blendjax.btt.faults import FaultPolicy
from blendjax.utils.timing import (
    SERVE_EVENTS,
    SERVE_STAGES,
    EventCounters,
    StageTimer,
)


def _serve_counts(counters):
    return {k: v for k, v in counters.snapshot().items()
            if k.startswith("serve_")}


# ---------------------------------------------------------------------------
# per-row-position decode: the tentpole model change
# ---------------------------------------------------------------------------


def _serial_decode(params, episodes, length, window, jit=True):
    """Per-episode scalar-position decode — the reference the batched
    per-row path must match."""
    import jax
    import jax.numpy as jnp

    from blendjax.models import seqformer

    step = functools.partial(
        seqformer.decode_step, compute_dtype=jnp.float32, window=window
    )
    if jit:
        step = jax.jit(step)
    out = []
    for ep in episodes:
        cache = seqformer.init_cache(
            params, 1, dtype=jnp.float32, length=length
        )
        preds = []
        for t in range(len(ep)):
            p, cache = step(params, cache, jnp.asarray(ep[t][None]))
            preds.append(np.asarray(p[0]))
        out.append(np.stack(preds))
    return out


def _batched_decode(params, episodes, length, window):
    """One per-row cache over every episode, stepped in sub-batches of
    whichever episodes still have observations — exactly the serving
    tier's gather -> decode_step -> scatter kernel."""
    import jax
    import jax.numpy as jnp

    from blendjax.models import seqformer

    n = len(episodes)
    cache = seqformer.init_cache(
        params, n, dtype=jnp.float32, length=length, per_row=True
    )

    @jax.jit
    def step(params, cache, idx, obs):
        rows = {
            "pos": cache["pos"][idx],
            "k": [k[idx] for k in cache["k"]],
            "v": [v[idx] for v in cache["v"]],
        }
        pred, new = seqformer.decode_step(
            params, rows, obs, compute_dtype=jnp.float32, window=window
        )
        cache = {
            "pos": cache["pos"].at[idx].set(new["pos"]),
            "k": [c.at[idx].set(nk)
                  for c, nk in zip(cache["k"], new["k"])],
            "v": [c.at[idx].set(nv)
                  for c, nv in zip(cache["v"], new["v"])],
        }
        return pred, cache

    got = [[] for _ in range(n)]
    for t in range(max(len(ep) for ep in episodes)):
        idx = np.asarray([i for i in range(n) if t < len(episodes[i])])
        obs = jnp.asarray(np.stack([episodes[i][t] for i in idx]))
        pred, cache = step(params, cache, jnp.asarray(idx), obs)
        for j, i in enumerate(idx):
            got[i].append(np.asarray(pred[j]))
    return [np.stack(p) for p in got], cache


@pytest.mark.parametrize(
    "kwargs,window",
    [
        (dict(), None),
        (dict(), 4),
        (dict(pos_encoding="rope"), None),
        (dict(pos_encoding="rope"), 4),
        (dict(n_kv_heads=2), None),
    ],
    ids=["learned", "learned-windowed", "rope", "rope-windowed", "gqa"],
)
def test_per_row_decode_matches_per_episode_serial(kwargs, window):
    """THE serving correctness bar: batched decode with per-row
    positions == per-episode serial decode, at heterogeneous episode
    lengths (rows sit at different timesteps every tick), with and
    without ``window`` ring caches.  f32 end to end; the only
    difference allowed is batched-matmul accumulation order (~1e-6)."""
    import jax

    from blendjax.models import seqformer

    params = seqformer.init(
        jax.random.PRNGKey(0), obs_dim=5, d_model=32, n_heads=4,
        n_layers=2, max_len=32, **kwargs,
    )
    rng = np.random.default_rng(0)
    episodes = [
        rng.standard_normal((t, 5)).astype(np.float32)
        for t in (7, 3, 5, 1)
    ]
    length = 16 if window is None else window
    want = _serial_decode(params, episodes, length, window)
    got, _ = _batched_decode(params, episodes, length, window)
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w, atol=1e-5, rtol=1e-5)


def test_per_row_cache_shapes_and_reset_masks_stale_rows():
    """``init_cache(per_row=True)`` gives a (B,) position vector, and
    rewinding ONE row's position to 0 is a full episode reset: the
    previous tenant's k/v rows sit at now-negative slot positions and
    never attend (no zeroing needed — the slot-position mask is the
    eviction)."""
    import jax
    import jax.numpy as jnp

    from blendjax.models import seqformer

    params = seqformer.init(
        jax.random.PRNGKey(0), obs_dim=5, d_model=32, n_heads=4,
        n_layers=2, max_len=32,
    )
    cache = seqformer.init_cache(
        params, 3, dtype=jnp.float32, length=8, per_row=True
    )
    assert cache["pos"].shape == (3,)
    rng = np.random.default_rng(1)
    old_ep = rng.standard_normal((5, 5)).astype(np.float32)
    # burn episode history into row 1
    for t in range(5):
        obs = jnp.asarray(np.stack([old_ep[t]] * 3))
        _, cache = seqformer.decode_step(
            params, cache, obs, compute_dtype=jnp.float32
        )
    # reset row 1 only, then serve a fresh episode on it
    cache["pos"] = cache["pos"].at[1].set(0)
    new_ep = rng.standard_normal((3, 5)).astype(np.float32)
    fresh = seqformer.init_cache(
        params, 1, dtype=jnp.float32, length=8
    )
    for t in range(3):
        obs = jnp.asarray(np.stack([new_ep[t]] * 3))
        p, cache = seqformer.decode_step(
            params, cache, obs, compute_dtype=jnp.float32
        )
        ref, fresh = seqformer.decode_step(
            params, fresh, jnp.asarray(new_ep[t][None]),
            compute_dtype=jnp.float32,
        )
        np.testing.assert_allclose(
            np.asarray(p[1]), np.asarray(ref[0]), atol=1e-5, rtol=1e-5
        )


# ---------------------------------------------------------------------------
# batched prefill admission (ISSUE-11): one teacher-forced pass == T
# serial decode steps
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "kwargs,window",
    [
        (dict(), None),
        (dict(), 4),
        (dict(pos_encoding="rope"), None),
        (dict(pos_encoding="rope"), 4),
        (dict(n_kv_heads=2), None),
    ],
    ids=["learned", "learned-windowed", "rope", "rope-windowed", "gqa"],
)
def test_prefill_admission_matches_serial_decode(kwargs, window):
    """Batched prefill (ONE teacher-forced pass filling the slot's KV
    rows) must agree with T serial ``decode_step``s across the PR-10
    parity matrix: the prefill prediction equals the T'th serial
    prediction, and every LATER step decodes identically — the cache
    the prefill wrote is byte-equivalent to the serially-built one."""
    import jax
    import jax.numpy as jnp

    from blendjax.models import seqformer
    from blendjax.serve.server import SeqFormerModel

    params = seqformer.init(
        jax.random.PRNGKey(0), obs_dim=5, d_model=32, n_heads=4,
        n_layers=2, max_len=32, **kwargs,
    )
    rng = np.random.default_rng(2)
    ep = rng.standard_normal((9, 5)).astype(np.float32)
    t0 = 5
    want = _serial_decode(params, [ep], 16, window)[0]
    model = SeqFormerModel(params, slots=3, length=16, window=window,
                           compute_dtype=jnp.float32)
    pred = model.prefill_rows(np.asarray([1]), ep[:t0])
    np.testing.assert_allclose(pred, want[t0 - 1], atol=1e-5, rtol=1e-5)
    for t in range(t0, len(ep)):
        got = model.step_rows(np.asarray([1]), ep[t][None])[0]
        np.testing.assert_allclose(got, want[t], atol=1e-5, rtol=1e-5)


def test_prefill_reset_end_to_end_and_validation():
    """The wire path: ``reset(prefix=...)`` admits mid-sequence (pred/
    pos in the reply, ``serve_prefills`` counted), and malformed or
    unservable prefixes error actionably with the slot RELEASED."""
    from blendjax.serve import (
        LinearModel,
        PolicyModel,
        ServeClient,
        start_server_thread,
    )

    counters = EventCounters()
    with start_server_thread(
        LinearModel(obs_dim=4, slots=1, seed=0), counters=counters,
    ) as h:
        c = ServeClient(h.address, fault_policy=FaultPolicy(max_retries=0))
        rng = np.random.default_rng(1)
        prefix = rng.standard_normal((5, 4)).astype(np.float32)
        ref = LinearModel(obs_dim=4, slots=1, seed=0)
        reply = c.reset(prefix=prefix)
        assert reply["pos"] == 5
        np.testing.assert_allclose(
            reply["pred"], ref.prefill_rows(np.asarray([0]), prefix)
        )
        r = c.step(prefix[0])
        assert r["pos"] == 5
        assert c.close_episode()
        # a bad prefix shape errors AND releases the (only) slot
        with pytest.raises(RuntimeError, match="prefix shape"):
            c.reset(prefix=np.zeros((3, 9), np.float32))
        c.reset(prefix=prefix)  # the slot came back
        assert c.close_episode()
        assert _serve_counts(counters)["serve_prefills"] == 2
        c.close()
    # stateless models refuse prefill admission actionably
    import jax

    from blendjax.models import policy

    params = policy.init(jax.random.PRNGKey(0), 4, 3)
    with start_server_thread(PolicyModel(params, 4)) as h:
        c = ServeClient(h.address, fault_policy=FaultPolicy(max_retries=0))
        with pytest.raises(RuntimeError, match="stateless"):
            c.reset(prefix=np.zeros((3, 4), np.float32))
        c.close()


# ---------------------------------------------------------------------------
# multi-model hosting (ISSUE-11)
# ---------------------------------------------------------------------------


def test_multi_model_server_per_model_pools_and_routing():
    """One server hosting two models: requests route by the envelope's
    model id (per-seed weight witness), each model owns its OWN slot
    pool (one model's exhaustion cannot deny the other), and an unknown
    model id errors actionably."""
    from blendjax.serve import LinearModel, ServeClient, start_server_thread

    obs = np.arange(4, dtype=np.float32)
    with start_server_thread({
        "a": LinearModel(obs_dim=4, slots=1, seed=0),
        "b": LinearModel(obs_dim=4, slots=2, seed=7),
    }) as h:
        ca = ServeClient(h.address, model="a",
                         fault_policy=FaultPolicy(max_retries=0))
        cb = ServeClient(h.address, model="b",
                         fault_policy=FaultPolicy(max_retries=0))
        hello = ca.hello()
        assert set(hello["models"]) == {"a", "b"}
        ca.reset()
        cb.reset()
        wa = LinearModel(obs_dim=4, slots=1, seed=0).w
        wb = LinearModel(obs_dim=4, slots=2, seed=7).w
        np.testing.assert_allclose(ca.step(obs)["pred"], obs @ wa)
        np.testing.assert_allclose(cb.step(obs)["pred"], obs @ wb)
        # model a is full (1 slot); model b still admits
        ca2 = ServeClient(h.address, model="a",
                          fault_policy=FaultPolicy(max_retries=0))
        with pytest.raises(RuntimeError, match="no free episode slot"):
            ca2.reset()
        cb2 = ServeClient(h.address, model="b")
        cb2.reset()
        bogus = ServeClient(h.address, model="nope",
                            fault_policy=FaultPolicy(max_retries=0))
        with pytest.raises(RuntimeError, match="unknown model"):
            bogus.reset()
        for c in (ca, cb, ca2, cb2, bogus):
            c.close()


def test_multi_model_single_workload_replies_identical():
    """The ISSUE-11 parity bar: a multi-model server hosting ONE model
    answers a single-model workload with replies identical to a plain
    single-model server — same keys, same values, same bytes in the
    prediction rows."""
    from blendjax.serve import LinearModel, ServeClient, start_server_thread

    def run_workload(address):
        c = ServeClient(address)
        out = []
        obs = np.linspace(-1, 1, 4).astype(np.float32)
        out.append(("hello", c.hello()))
        c.reset()
        out.append(("reset", {"slot": c.slot, "episode": c.episode}))
        for t in range(3):
            out.append(("step", c.step(obs + t)))
        out.append(("close", {"closed": c.close_episode()}))
        c.close()
        return out

    with start_server_thread(LinearModel(obs_dim=4, slots=2, seed=0)) as h:
        single = run_workload(h.address)
    with start_server_thread(
        {"linear": LinearModel(obs_dim=4, slots=2, seed=0)}
    ) as h:
        multi = run_workload(h.address)
    assert len(single) == len(multi)
    for (ks, vs), (km, vm) in zip(single, multi):
        assert ks == km
        assert set(vs) == set(vm), (ks, set(vs), set(vm))
        for key in vs:
            if isinstance(vs[key], np.ndarray):
                assert vs[key].tobytes() == vm[key].tobytes(), (ks, key)
            elif key not in ("pid", "shm"):
                # pid and the shm endpoint advertisement are process
                # identity, not workload semantics
                assert vs[key] == vm[key], (ks, key)


# ---------------------------------------------------------------------------
# PolicyServer: batching, slots, counters
# ---------------------------------------------------------------------------


def test_linear_server_end_to_end_counters_and_stages():
    from blendjax.serve import LinearModel, ServeClient, start_server_thread

    counters, timer = EventCounters(), StageTimer()
    with start_server_thread(
        LinearModel(obs_dim=4, slots=2, seed=0),
        counters=counters, timer=timer,
    ) as h:
        c = ServeClient(h.address)
        hello = c.hello()
        assert hello["model"] == "linear" and hello["slots"] == 2
        c.reset()
        obs = np.arange(4, dtype=np.float32)
        r0, r1 = c.step(obs), c.step(obs)
        assert (r0["pos"], r1["pos"]) == (0, 1)
        np.testing.assert_allclose(r1["pred"], r0["pred"] + 1.0)
        # slot exhaustion: 1 live + 2 more resets -> second one denied
        c2 = ServeClient(h.address, fault_policy=FaultPolicy(max_retries=0))
        c2.reset()
        with pytest.raises(RuntimeError, match="no free episode slot"):
            c2.rpc("reset")
        # close frees the slot; the next reset succeeds
        assert c.close_episode()
        c2.rpc("reset")
        # stepping an unknown slot errors actionably
        with pytest.raises(RuntimeError, match="unknown episode slot"):
            c2.step(obs, slot=99)
        # the reply counter lands AFTER the socket send, so the client
        # can observe its reply a beat before the server's increment —
        # wait out that window before asserting the exact invariant
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            snap = _serve_counts(counters)
            if snap["serve_requests"] == (
                snap["serve_replies"] + snap.get("serve_dup_inflight", 0)
            ):
                break
            time.sleep(0.01)
        assert snap["serve_slot_denied"] == 1
        assert snap["serve_errors"] >= 2  # denial + unknown slot
        assert snap["serve_resets"] == 3
        assert snap["serve_batches"] >= 2
        # every admitted request is answered exactly once — except a
        # duplicate of a still-queued request, which is dropped at
        # admission and answered by the original's reply (a loaded CI
        # box can push a client into that retry)
        assert snap["serve_requests"] == (
            snap["serve_replies"] + snap.get("serve_dup_inflight", 0)
        )
        summary = timer.summary()
        for stage in SERVE_STAGES:
            assert summary[stage]["count"] > 0, stage
        c.close()
        c2.close()


def test_slot_ttl_eviction():
    from blendjax.serve import LinearModel, ServeClient, start_server_thread

    counters = EventCounters()
    with start_server_thread(
        LinearModel(obs_dim=4, slots=1, seed=0),
        counters=counters, slot_ttl_s=0.2,
    ) as h:
        c1 = ServeClient(h.address)
        c1.reset()
        time.sleep(0.3)
        # the only slot is idle past the ttl: a new episode evicts it
        c2 = ServeClient(h.address)
        c2.reset()
        assert _serve_counts(counters)["serve_evictions"] == 1
        # the evicted episode's slot was REASSIGNED: the stale client's
        # lease refuses the step instead of advancing the new tenant
        with pytest.raises(RuntimeError, match="stale episode lease"):
            c1.step(np.zeros(4, np.float32))
        # ... and its stale close cannot kill the new episode either
        assert not c1.close_episode()
        c2.step(np.zeros(4, np.float32))
        c1.close()
        c2.close()


def test_seqformer_server_concurrent_episodes_match_serial():
    """End-to-end world-model serving: concurrent episode clients at
    heterogeneous lengths through the batching server equal per-episode
    serial decode — the tier-level restatement of the kernel parity."""
    import jax

    from blendjax.models import seqformer
    from blendjax.serve import (
        SeqFormerModel,
        ServeClient,
        start_server_thread,
    )

    params = seqformer.init(
        jax.random.PRNGKey(0), obs_dim=5, d_model=32, n_heads=4,
        n_layers=2, max_len=32,
    )
    rng = np.random.default_rng(1)
    episodes = [
        rng.standard_normal((t, 5)).astype(np.float32) for t in (6, 3, 5)
    ]
    want = _serial_decode(params, episodes, 16, None)
    counters = EventCounters()
    with start_server_thread(
        SeqFormerModel(params, slots=4, length=16), counters=counters,
    ) as h:
        outs = [[] for _ in episodes]

        def run(i):
            c = ServeClient(h.address, timeoutms=20000)
            c.reset()
            for t in range(len(episodes[i])):
                outs[i].append(c.step(episodes[i][t])["pred"])
            c.close_episode()
            c.close()

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(len(episodes))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
    for i, w in enumerate(want):
        np.testing.assert_allclose(
            np.stack(outs[i]), w, atol=1e-5, rtol=1e-5
        )
    assert _serve_counts(counters)["serve_batches"] >= 3


def test_policy_server_stateless_greedy_logits():
    import jax

    from blendjax.models import policy
    from blendjax.serve import PolicyModel, ServeClient, start_server_thread

    params = policy.init(jax.random.PRNGKey(0), 6, 3)
    counters = EventCounters()
    with start_server_thread(PolicyModel(params, 6),
                             counters=counters) as h:
        c = ServeClient(h.address)
        assert c.hello()["slots"] == 0
        assert c.reset() == -1  # stateless: no slot pool
        obs = np.linspace(-1, 1, 6).astype(np.float32)
        pred = c.step(obs)["pred"]
        want = np.asarray(policy.logits(params, obs[None]))[0]
        np.testing.assert_allclose(pred, want, atol=1e-5, rtol=1e-5)
        # stateless episodes still reconcile: the real close counts,
        # a duplicate close of the same episode does not
        assert c.stats()["live_episodes"] == 1
        assert c.close_episode()
        stale = ServeClient(h.address)
        stale.slot, stale.episode = -1, 999  # never admitted
        assert not stale.close_episode()
        snap = _serve_counts(counters)
        assert snap["serve_closes"] == 1 == snap["serve_resets"]
        stale.close()
        c.close()


# ---------------------------------------------------------------------------
# int8 serving parity (satellite)
# ---------------------------------------------------------------------------


def _trained_seqformer(key, obs_dim=5, steps=20):
    import jax
    import jax.numpy as jnp
    import optax

    from blendjax.models import seqformer
    from blendjax.models.train import TrainState, make_train_step

    params = seqformer.init(
        key, obs_dim=obs_dim, d_model=32, n_heads=4, n_layers=2,
        max_len=32,
    )
    batch = seqformer.make_episode_batch(
        jax.random.normal(jax.random.PRNGKey(9), (4, 17, obs_dim),
                          jnp.float32)
    )
    state = TrainState.create(params, optax.adam(1e-2))
    step = make_train_step(
        lambda p, b: seqformer.loss_fn(p, b, compute_dtype=jnp.float32),
        optax.adam(1e-2),
    )
    for _ in range(steps):
        state, _ = step(state, batch)
    return jax.device_get(state.params)


def test_int8_served_predictions_track_float():
    """The int8 serving path (quantize_seqformer through the same
    batched per-row decode) agrees with the float server within the
    tolerance the ops/quant tests use on a TRAINED model (5% of the
    output scale — random weights overstate quantization error)."""
    import jax

    from blendjax.serve import (
        SeqFormerModel,
        ServeClient,
        start_server_thread,
    )

    params = _trained_seqformer(jax.random.PRNGKey(0))
    rng = np.random.default_rng(3)
    ep = rng.standard_normal((6, 5)).astype(np.float32)

    def serve_episode(model):
        with start_server_thread(model) as h:
            c = ServeClient(h.address, timeoutms=20000)
            c.reset()
            preds = [c.step(ep[t])["pred"] for t in range(len(ep))]
            c.close_episode()
            c.close()
        return np.stack(preds)

    ref = serve_episode(SeqFormerModel(params, slots=2, length=16))
    got = serve_episode(
        SeqFormerModel(params, slots=2, length=16, int8=True)
    )
    err = float(np.abs(got - ref).max())
    scale = float(np.abs(ref).max())
    assert err < 0.05 * max(scale, 1.0), (err, scale)


def test_int8_policy_logits_track_float():
    import jax

    from blendjax.models import policy
    from blendjax.ops.quant import quantize_policy

    params = policy.init(jax.random.PRNGKey(1), 6, 4)
    obs = np.random.default_rng(0).standard_normal((16, 6)).astype(
        np.float32
    )
    ref = np.asarray(policy.logits(params, obs))
    got = np.asarray(policy.logits(quantize_policy(params), obs))
    err = float(np.abs(got - ref).max())
    scale = float(np.abs(ref).max())
    assert err < 0.05 * max(scale, 1.0), (err, scale)


def test_malformed_requests_error_but_server_survives():
    """Garbage must come back as error replies, never kill the serving
    thread: unknown command, step without obs, ragged obs shape."""
    from blendjax.serve import LinearModel, ServeClient, start_server_thread

    counters = EventCounters()
    with start_server_thread(
        LinearModel(obs_dim=4, slots=2, seed=0), counters=counters,
    ) as h:
        c = ServeClient(h.address, fault_policy=FaultPolicy(max_retries=0))
        c.reset()
        with pytest.raises(RuntimeError, match="unknown serve command"):
            c.rpc("frobnicate")
        with pytest.raises(RuntimeError, match="obs"):
            c.rpc("step", {"slot": c.slot, "episode": c.episode})
        with pytest.raises(RuntimeError, match="obs shape"):
            c.rpc("step", {"slot": c.slot, "episode": c.episode,
                           "obs": np.zeros(7, np.float32)},
                  raw_buffers=True)
        # ... and the episode still serves afterwards
        r = c.step(np.zeros(4, np.float32))
        assert r["pos"] == 0
        assert _serve_counts(counters)["serve_errors"] == 3
        # undecodable FRAMES (a garbling proxy, a rogue peer) must not
        # kill the serve loop either: raw garbage, then a real step
        import zmq

        rogue = zmq.Context.instance().socket(zmq.DEALER)
        rogue.setsockopt(zmq.LINGER, 0)
        rogue.connect(h.address)
        rogue.send_multipart([b"", b"not-pickle-at-all"])
        rogue.close(0)
        r = c.step(np.zeros(4, np.float32))
        assert r["pos"] == 1
        c.close()


# ---------------------------------------------------------------------------
# exactly-once through wire faults (satellite)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
@pytest.mark.parametrize("transport", ["tcp", "shm"])
def test_exactly_once_through_drop_dup_and_stall(transport):
    """Wire faults between ServeClient and PolicyServer must each yield
    EXACTLY one applied step per submitted request — the LinearModel's
    position counter rides every prediction, so a double-applied step
    shifts every later value and the reference comparison catches it.
    Parametrized over BOTH wires (ISSUE-12): the ``tcp`` arm injects at
    the TCP chunk layer (ChaosProxy, shm upgrade pinned off), the
    ``shm`` arm at the ring frame layer (ShmChaos) — the shared
    ``btt/rpc.py`` discipline is locked on each, not just the one it
    was written against."""
    from blendjax.serve import LinearModel, ServeClient, start_server_thread

    counters = EventCounters()
    model = LinearModel(obs_dim=4, slots=2, seed=0)
    ref = LinearModel(obs_dim=4, slots=2, seed=0)
    obs = np.arange(4, dtype=np.float32)
    with start_server_thread(model, counters=counters) as h:
        if transport == "tcp":
            _serve_chaos_tcp_arm(h, counters, ref, obs)
        else:
            _serve_chaos_shm_arm(h, counters, ref, obs)


def _serve_chaos_tcp_arm(h, counters, ref, obs):
    from blendjax.btt.chaos import ChaosProxy
    from blendjax.serve import ServeClient

    with ChaosProxy(h.address) as proxy:
        client = ServeClient(
            proxy.address,
            fault_policy=FaultPolicy(
                max_retries=4, backoff_base=0.02, backoff_max=0.1,
                circuit_threshold=0, seed=1,
            ),
            counters=counters, timeoutms=400, shm=False,
        )
        client.reset()
        ref.reset_rows(np.asarray([0]))
        preds = []
        for t in range(20):
            if t == 5:
                proxy.drop_next("down")   # lose a reply -> retry
            if t == 9:
                proxy.dup_next("up")      # duplicate a request
            if t == 13:
                proxy.stall()

                def unstall():
                    time.sleep(0.6)  # past the 400 ms attempt
                    proxy.resume()

                threading.Thread(target=unstall, daemon=True).start()
            preds.append(client.step(obs)["pred"])
        want = [ref.step_rows(np.asarray([0]), obs[None])[0]
                for _ in range(20)]
        np.testing.assert_allclose(np.stack(preds), np.stack(want))
        snap = counters.snapshot()
        # the faults actually happened and were healed by the
        # exactly-once machinery, not by luck
        assert snap.get("retries", 0) >= 2
        assert (
            snap.get("serve_cache_hits", 0)
            + snap.get("serve_dup_inflight", 0)
        ) >= 1
        client.close()


def _serve_chaos_shm_arm(h, counters, ref, obs):
    """Frame-layer faults on the upgraded channel: a duplicated request
    (stays on shm — reply-cache/in-queue dedupe), then a dropped reply
    whose same-mid retry rides the DEMOTED ZMQ path and is answered
    from the server's reply cache (exactly-once ACROSS the transports
    — the respawn-heal discipline in miniature), then the re-upgrade
    onto a fresh ring generation."""
    from blendjax.btt.shm_rpc import ShmChaos, enabled
    from blendjax.serve import ServeClient

    if not enabled():
        pytest.skip("shm rpc unavailable on this host")
    chaos = ShmChaos(seed=1)
    client = ServeClient(
        h.address,
        fault_policy=FaultPolicy(
            max_retries=4, backoff_base=0.02, backoff_max=0.1,
            circuit_threshold=0, seed=1,
        ),
        counters=counters, timeoutms=400, shm_chaos=chaos,
    )
    client.reset()
    ref.reset_rows(np.asarray([0]))
    preds = []
    for t in range(20):
        if t == 4:
            assert client.transport == "shm", "upgrade never happened"
            chaos.dup_next("up")      # duplicate a request in the ring
        if t == 8:
            chaos.drop_next("down")   # lose a reply -> timeout ->
            #                           demote -> same-mid retry on zmq
        preds.append(client.step(obs)["pred"])
    # the dropped reply demoted the channel: its retry rode ZMQ
    assert client.transport == "tcp"
    want = [ref.step_rows(np.asarray([0]), obs[None])[0]
            for _ in range(20)]
    np.testing.assert_allclose(np.stack(preds), np.stack(want))
    snap = counters.snapshot()
    assert snap.get("retries", 0) >= 1
    assert (
        snap.get("serve_cache_hits", 0)
        + snap.get("serve_dup_inflight", 0)
    ) >= 1, snap
    assert chaos.dropped >= 1 and chaos.duplicated >= 1
    # generation heal: once the (live) server answers on ZMQ and the
    # backoff elapses, the channel re-upgrades onto fresh rings
    time.sleep(1.1)
    for _ in range(3):
        preds.append(client.step(obs)["pred"])
    assert client.transport == "shm", "channel never re-upgraded"
    assert client._chan.generations == 2
    np.testing.assert_allclose(
        np.stack(preds[-3:]),
        np.stack([ref.step_rows(np.asarray([0]), obs[None])[0]
                  for _ in range(3)]),
    )
    client.close()


@pytest.mark.chaos
def test_sigkilled_server_respawned_by_watchdog_resumes_after_reset():
    """The serving tier's crash contract: SIGKILL the server process,
    let ``FleetWatchdog(restart=True)`` respawn it (same command line,
    seed-deterministic weights), and a client resumes after ``reset()``
    — its old slot is gone (the error names it), its new episode serves
    correctly, and the fault counters are pinned."""
    from blendjax.btt.chaos import kill_instance
    from blendjax.btt.watchdog import FleetWatchdog
    from blendjax.serve import ServeClient, ServerProcess

    counters = EventCounters()
    obs = np.arange(4, dtype=np.float32)
    with ServerProcess(model="linear", obs_dim=4, slots=4) as sp:
        with FleetWatchdog(sp, interval=0.2, restart=True):
            client = ServeClient(
                sp.address,
                fault_policy=FaultPolicy(
                    max_retries=1, backoff_base=0.05, backoff_max=0.2,
                    circuit_threshold=0, seed=2,
                ),
                counters=counters, timeoutms=500,
            )
            client.reset()
            first = client.step(obs)
            assert first["pos"] == 0

            kill_instance(sp, 0)
            # steps against the dead (then fresh) server fail with
            # either a transport timeout (server still down) or an
            # unknown-slot error (the watchdog's respawn won the race)
            # — never a silent wrong answer; reset-and-resume recovers
            deadline = time.monotonic() + 30
            recovered = False
            failures = []
            while time.monotonic() < deadline:
                try:
                    client.step(obs)
                except (TimeoutError, RuntimeError) as exc:
                    failures.append(exc)
                    try:
                        client.reset_channel()
                        client.reset(timeout_ms=500)
                        recovered = True
                        break
                    except (TimeoutError, RuntimeError) as exc2:
                        failures.append(exc2)
                        time.sleep(0.1)
            assert recovered, "client never recovered after respawn"
            r = client.step(obs)
            assert r["pos"] == 0  # a FRESH episode on the new server
            np.testing.assert_allclose(r["pred"], first["pred"])
            # the kill was OBSERVED, one way or the other: transport
            # timeouts pinned in the fault counters, or the fresh
            # server's unknown-slot refusal
            snap = counters.snapshot()
            assert failures, "kill was never observed by the client"
            assert snap.get("timeouts", 0) >= 1 or any(
                "episode slot" in str(e) for e in failures
            ), (snap, [str(e) for e in failures])
            client.close()


# ---------------------------------------------------------------------------
# telemetry plane integration
# ---------------------------------------------------------------------------


def test_hub_scrapes_server_remotely_and_locally():
    from blendjax.obs.hub import TelemetryHub
    from blendjax.serve import LinearModel, ServeClient, start_server_thread

    counters, timer = EventCounters(), StageTimer()
    with start_server_thread(
        LinearModel(obs_dim=4, slots=2, seed=0),
        counters=counters, timer=timer,
    ) as h:
        c = ServeClient(h.address)
        c.reset()
        for _ in range(3):
            c.step(np.zeros(4, np.float32))
        # remote registration: the hub pulls the telemetry RPC per
        # scrape (how a separate scraper process would see the server)
        hub = TelemetryHub()
        c.register_with_hub(hub, "serve")
        snap = hub.scrape()
        assert snap["counters"]["serve_batches"] >= 1
        assert snap["stages"]["compute"]["count"] >= 1
        # histogram-backed percentiles, not zero-fills: the serve
        # stages carry real p50/p99 through the remote merge
        assert snap["stages"]["compute"]["p99_ms"] > 0.0
        assert (snap["stages"]["compute"]["p99_ms"]
                >= snap["stages"]["compute"]["p50_ms"])
        assert "serve" in snap["components"]
        # every serve counter is present even when zero
        for name in SERVE_EVENTS:
            assert name in snap["counters"], name
        c.close()


def test_trace_spans_ride_the_correlation_id():
    from blendjax.obs.spans import SpanRecorder, span_trace
    from blendjax.serve import LinearModel, ServeClient, start_server_thread

    rec = SpanRecorder()
    with start_server_thread(LinearModel(obs_dim=4, slots=2)) as h:
        c = ServeClient(h.address, span_recorder=rec)
        c.reset()
        c.step(np.zeros(4, np.float32))
        c.close()
    spans = rec.drain()
    names = {s["name"] for s in spans}
    assert "serve:step" in names and "serve_rpc:step" in names
    # server- and client-side spans of one RPC share the trace id
    srv = [s for s in spans if s["name"] == "serve:step"]
    cli = [s for s in spans if s["name"] == "serve_rpc:step"]
    assert span_trace(srv[0]) == span_trace(cli[0]) is not None


# ---------------------------------------------------------------------------
# bench schema lock (satellite)
# ---------------------------------------------------------------------------


def test_bench_headline_carries_serve_metrics():
    import bench

    sb = {
        "phase": "serve_bench", "model": "seqformer", "clients": 8,
        "serve_qps": 2650.0, "serve_p50_ms": 2.4, "serve_p99_ms": 6.4,
        "serve_batch_x": 3.1, "serve_int8_x": 0.98,
        "serve_qps_modes": {"batched": 2650.0, "serial": 850.0,
                            "int8": 2600.0},
        "stages": {},
    }
    out = bench.assemble({}, host_fallback=lambda: 1.0, serve_bench=sb)
    assert out["serve_bench"]["serve_qps"] == 2650.0
    line = bench.headline(out)
    assert line["serve_qps"] == 2650.0
    assert line["serve_p99_ms"] == 6.4
    assert line["serve_batch_x"] == 3.1
    assert len(json.dumps(line)) + 1 <= bench.HEADLINE_BYTE_BUDGET


def test_serve_bench_emits_locked_schema():
    from benchmarks._common import SERVE_BENCH_KEYS
    from benchmarks.serve_benchmark import measure

    rec = measure(seconds=1.2, clients=4, model="linear", rounds=1)
    assert all(k in rec for k in SERVE_BENCH_KEYS), [
        k for k in SERVE_BENCH_KEYS if k not in rec
    ]
    assert rec["serve_qps"] > 0
    assert rec["serve_p99_ms"] >= rec["serve_p50_ms"]
    assert rec["serve_batch_x"] is not None
    for stage in SERVE_STAGES:
        assert stage in rec["stages"], stage
