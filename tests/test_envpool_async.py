"""Async pipelined EnvPool tests (docs/rl_stepping.md).

Covers the step_async/step_wait DEALER path end to end against the real
producer stack (fake-Blender fleet speaking the real wire protocol):
lock-step bit-identity, ready-first partial batches, out-of-order reply
routing through ChaosProxy stalls, mid-flight kill -> quarantine ->
re-admission at full pipeline depth (both quarantine and strict modes),
and the producer-side correlation-id dedupe that makes retried ``step``
requests exactly-once.
"""

import time
import types

import numpy as np
import pytest

from blendjax.btt.chaos import ChaosProxy, kill_instance, wait_env_ready
from blendjax.btt.envpool import EnvPool, launch_env_pool
from blendjax.btt.faults import FaultPolicy
from blendjax.btt.launcher import BlenderLauncher
from blendjax.btt.supervise import FleetSupervisor
from blendjax.utils.timing import EventCounters
from helpers import BLEND_SCRIPTS, FAKE_BLENDER

ENV_SCRIPT = f"{BLEND_SCRIPTS}/env.blend.py"


@pytest.fixture
def fake_blender(monkeypatch):
    monkeypatch.setenv("BLENDJAX_BLENDER", FAKE_BLENDER)


def _drive_lockstep(pool, action_rounds):
    out = []
    for actions in action_rounds:
        obs, rew, done, infos = pool.step(list(actions))
        out.append((
            np.asarray(obs).copy(), np.asarray(rew).copy(),
            np.asarray(done).copy(),
            [(i.get("time"), i.get("frame")) for i in infos],
        ))
    return out


def _drive_async(pool, action_rounds):
    out = []
    for actions in action_rounds:
        pool.step_async(list(actions))
        obs, rew, done, infos = pool.step_wait_full()
        out.append((
            np.asarray(obs).copy(), np.asarray(rew).copy(),
            np.asarray(done).copy(),
            [(i.get("time"), i.get("frame")) for i in infos],
        ))
    return out


def test_async_lockstep_bit_identical(fake_blender):
    """The acceptance parity check: driven over the same deterministic
    fleet (EchoEnv), the async path's full-batch mode produces byte-for-
    byte the transitions the lock-step ``step()`` path produces —
    including an autoreset boundary inside the window."""
    rounds = [
        [1.0, 2.0], [2.0, 3.0], [3.0, 1.0], [1.5, 2.5],  # crosses done@6
        [4.0, 5.0], [0.5, 0.25], [6.0, 7.0],
    ]
    with launch_env_pool(
        scene="", script=ENV_SCRIPT, num_instances=2, background=True,
        horizon=6, timeoutms=30000, start_port=13200, pipeline_depth=2,
    ) as pool:
        pool.reset()
        lockstep = _drive_lockstep(pool, rounds)
        pool.reset()  # restart the episode: the fixture is deterministic
        asynced = _drive_async(pool, rounds)
    for (lo, lr, ld, li), (ao, ar, ad, ai) in zip(lockstep, asynced):
        np.testing.assert_array_equal(lo, ao)
        assert lo.dtype == ao.dtype
        np.testing.assert_array_equal(lr, ar)
        assert lr.dtype == ar.dtype
        np.testing.assert_array_equal(ld, ad)
        assert li == ai  # per-env clocks advanced identically


def test_pipelined_depth2_ready_first_and_monotonic(fake_blender):
    """Depth-2 pipelining: ready-first collection with indices, per-env
    FIFO ordering, monotonic per-env clocks, and depth accounting."""
    with launch_env_pool(
        scene="", script=ENV_SCRIPT, num_instances=2, background=True,
        horizon=1_000_000, timeoutms=30000, start_port=13220,
        pipeline_depth=2,
    ) as pool:
        pool.reset()
        pool.step_async([1.0, 2.0])
        pool.step_async([3.0, 4.0])
        assert pool.inflight == [2, 2]
        # over-depth submission is a programming error
        with pytest.raises(RuntimeError, match="in flight"):
            pool.step_async([9.0, 9.0])
        times = {0: [], 1: []}
        seen = {0: [], 1: []}
        collected = 0
        while collected < 4:
            idx, obs, rew, done, infos = pool.step_wait(min_ready=1)
            assert len(idx) >= 1
            for j, i in enumerate(idx):
                i = int(i)
                times[i].append(infos[j]["time"])
                seen[i].append(float(np.asarray(obs).reshape(-1)[j]))
                assert infos[j]["healthy"]
            collected += len(idx)
        assert pool.inflight == [0, 0]
        # each transition landed at the env that was sent its action,
        # oldest first (EchoEnv: obs == the action that produced it)
        assert seen[0] == [1.0, 3.0]
        assert seen[1] == [2.0, 4.0]
        for ts in times.values():
            assert ts == sorted(ts) and len(set(ts)) == len(ts)
        # lock-step step() refuses to interleave with a live pipeline
        pool.step_async([5.0, 5.0])
        with pytest.raises(RuntimeError, match="in flight"):
            pool.step([6.0, 6.0])
        pool.step_wait()
        # mismatched indices/actions lengths
        with pytest.raises(ValueError, match="expected 2 actions"):
            pool.step_async([1.0])
        with pytest.raises(ValueError, match="indices"):
            pool.step_async([1.0, 2.0], indices=[0])


@pytest.mark.chaos
def test_out_of_order_replies_route_by_correlation(fake_blender):
    """ChaosProxy stalls reorder completion across envs: replies must
    land at the right env index regardless of arrival order, with
    ``env_times`` monotonic per env."""
    policy = FaultPolicy(max_retries=1, deadline_s=5.0, jitter=0.0,
                         circuit_threshold=0, seed=3)
    with BlenderLauncher(
        scene="", script=ENV_SCRIPT, num_instances=3,
        named_sockets=["GYM"], start_port=13240, background=True,
        instance_args=[["--horizon", "100000"]] * 3,
    ) as bl:
        addrs = bl.launch_info.addresses["GYM"]
        wait_env_ready(addrs)
        with ChaosProxy(addrs[0], seed=5) as proxy:
            counters = EventCounters()
            pool = EnvPool(
                [proxy.address, addrs[1], addrs[2]], timeoutms=10000,
                fault_policy=policy, counters=counters, pipeline_depth=2,
            )
            try:
                pool.reset()
                times = {i: [] for i in range(3)}
                for round_no in range(4):
                    actions = [10.0 * (round_no + 1) + i for i in range(3)]
                    proxy.stall()  # env 0's replies held back
                    pool.step_async(actions)
                    # the two unstalled envs complete first: ready-first
                    # returns them without blocking on the straggler
                    idx, obs, rew, done, infos = pool.step_wait(min_ready=2)
                    got = {int(i) for i in idx}
                    assert 0 not in got and got <= {1, 2}
                    for j, i in enumerate(idx):
                        i = int(i)
                        assert float(np.asarray(obs)[j]) == actions[i]
                        times[i].append(infos[j]["time"])
                    proxy.resume()
                    # the straggler lands at ITS index, out of submission
                    # order vs the batch that already returned
                    while len(times[0]) <= round_no:
                        idx, obs, rew, done, infos = pool.step_wait(
                            min_ready=1
                        )
                        for j, i in enumerate(idx):
                            i = int(i)
                            assert float(np.asarray(obs)[j]) == actions[i]
                            times[i].append(infos[j]["time"])
                assert counters.get("quarantines") == 0
                for i, ts in times.items():
                    assert len(ts) == 4
                    assert ts == sorted(ts) and len(set(ts)) == len(ts), (
                        f"env {i} clock not monotonic: {ts}"
                    )
                assert pool.healthy.all()
            finally:
                pool.close()


def _policy(**kw):
    base = dict(
        max_retries=1, deadline_s=0.6, backoff_base=0.05,
        backoff_factor=2.0, backoff_max=0.2, jitter=0.25,
        circuit_threshold=0, seed=7,
    )
    base.update(kw)
    return FaultPolicy(**base)


@pytest.mark.chaos
def test_kill_mid_flight_quarantine_and_full_depth_readmission(fake_blender):
    """THE pipelined chaos acceptance: kill 1 of 3 producers with
    requests in flight at depth 2.  The pipeline drains into synthetic
    transitions (exactly one ``done=True``), survivors keep completing,
    the supervisor respawns + re-admits, and the env rejoins at full
    pipeline depth serving real transitions."""
    with BlenderLauncher(
        scene="", script=ENV_SCRIPT, num_instances=3,
        named_sockets=["GYM"], start_port=13260, background=True,
        instance_args=[["--horizon", "100000"]] * 3,
    ) as bl:
        addrs = bl.launch_info.addresses["GYM"]
        wait_env_ready(addrs)
        counters = EventCounters()
        # the victim sits behind a chaos proxy so the kill provably lands
        # while its two requests are in flight (stall first, then kill)
        with ChaosProxy(addrs[1], seed=11) as proxy:
            pool = EnvPool(
                [addrs[0], proxy.address, addrs[2]], timeoutms=10000,
                fault_policy=_policy(), counters=counters, pipeline_depth=2,
            )
            with FleetSupervisor(
                bl, pool=pool, interval=3.0, heal_interval=0.05,
                counters=counters,
            ) as sup:
                try:
                    _run_kill_mid_flight(bl, pool, sup, counters, proxy)
                finally:
                    pool.close()


def _run_kill_mid_flight(bl, pool, sup, counters, proxy):
    pool.reset()
    pool.step_async([1.0, 1.0, 1.0])
    pool.step_async([2.0, 2.0, 2.0])
    idx, *_ = pool.step_wait()  # clean prime: 6 transitions
    assert len(idx) == 6
    assert counters.get("quarantines") == 0

    # two requests provably in flight to the victim at death: the
    # stalled proxy holds them short of the producer
    proxy.stall()
    pool.step_async([3.0, 3.0, 3.0])
    pool.step_async([4.0, 4.0, 4.0])
    assert pool.inflight == [2, 2, 2]
    kill_instance(bl, 1)
    proxy.resume()  # re-admission must flow once it respawns

    env1_dones = 0
    env1_synthetic = 0
    readmitted = False
    deadline = time.monotonic() + 120
    while not readmitted and time.monotonic() < deadline:
        idx, obs, rew, done, infos = pool.step_wait(min_ready=3)
        for j, i in enumerate(idx):
            i = int(i)
            if i != 1:
                assert infos[j]["healthy"]  # survivors never poisoned
                continue
            if done[j]:
                env1_dones += 1
            if not infos[j].get("healthy", True):
                env1_synthetic += 1
                assert rew[j] == 0.0
            if infos[j].get("readmitted"):
                readmitted = True
        pool.step_async([5.0] * len(idx), indices=list(idx))
    assert readmitted, f"no re-admission; health={sup.health()}"
    # the interrupted episode closed exactly once
    assert env1_dones == 1
    assert env1_synthetic >= 1

    # drain, then prove full-depth operation post-heal
    pool.step_wait()
    pool.step_async([7.0, 8.0, 9.0])
    pool.step_async([7.5, 8.5, 9.5])
    assert pool.inflight == [2, 2, 2]
    got = {0: [], 1: [], 2: []}
    while any(len(v) < 2 for v in got.values()):
        idx, obs, rew, done, infos = pool.step_wait(min_ready=1)
        for j, i in enumerate(idx):
            got[int(i)].append(float(np.asarray(obs)[j]))
            assert infos[j]["healthy"]
    assert got[1] == [8.0, 8.5]  # real transitions again

    h = sup.health()
    assert h["quarantines"] == 1
    assert h["readmissions"] == 1
    assert h["deaths"] == 1 and h["restarts"] == 1
    # the in-flight requests were drained into synthetics, not retried
    # into the corpse forever
    assert h["inflight_discards"] >= 2
    assert h["pipeline_depth"] == 2
    assert h["inflight_per_env"] == [0, 0, 0]
    assert h["inflight_total"] == 0


@pytest.mark.chaos
def test_kill_mid_flight_strict_mode_raises_naming_env(fake_blender):
    """quarantine=False: a producer dying with pipeline requests in
    flight fails the wait with a ``TimeoutError`` naming the env, and
    already-completed transitions survive for a later collection."""
    with BlenderLauncher(
        scene="", script=ENV_SCRIPT, num_instances=2,
        named_sockets=["GYM"], start_port=13290, background=True,
        instance_args=[["--horizon", "100000"]] * 2,
    ) as bl:
        addrs = bl.launch_info.addresses["GYM"]
        wait_env_ready(addrs)
        with ChaosProxy(addrs[0], seed=13) as proxy:
            pool = EnvPool(
                [proxy.address, addrs[1]], timeoutms=10000,
                fault_policy=_policy(max_retries=0), quarantine=False,
                counters=EventCounters(), pipeline_depth=2,
            )
            try:
                pool.reset()
                # hold env 0's requests on the wire, then kill it: the
                # death provably lands with its pipeline full
                proxy.stall()
                pool.step_async([1.0, 2.0])
                pool.step_async([3.0, 4.0])
                assert pool.inflight == [2, 2]
                kill_instance(bl, 0)
                with pytest.raises(TimeoutError, match="environment 0"):
                    deadline = time.monotonic() + 60
                    while time.monotonic() < deadline:
                        pool.step_wait(min_ready=4, timeout_ms=5000)
                # env 1's completed transitions were committed, not lost
                idx, obs, rew, done, infos = pool.step_wait(
                    min_ready=1, timeout_ms=5000
                )
                assert {int(i) for i in idx} == {1}
                assert [float(v) for v in np.asarray(obs)] == [2.0, 4.0]
            finally:
                pool.close()


def test_agent_dedupes_resent_correlated_step():
    """Producer-side exactly-once: a re-sent ``step`` carrying the same
    correlation id (the consumer's retry path) is answered from the
    reply cache instead of simulating the frame twice; the id is echoed
    in every reply."""
    import zmq

    from blendjax import wire
    from blendjax.btb.env import BaseEnv, RemoteControlledAgent
    from helpers.producers import free_port

    addr = f"tcp://127.0.0.1:{free_port()}"
    agent = RemoteControlledAgent(addr, timeoutms=1000)
    ctx = zmq.Context.instance()
    dealer = ctx.socket(zmq.DEALER)
    dealer.setsockopt(zmq.LINGER, 0)
    dealer.setsockopt(zmq.RCVTIMEO, 5000)
    dealer.connect(addr)
    env = types.SimpleNamespace(state=BaseEnv.STATE_RUN)
    try:
        req_a = {"cmd": "step", "action": 3.5}
        mid_a = wire.stamp_message_id(req_a)
        wire.send_message_dealer(dealer, req_a)
        # frame k: agent consumes the request and applies the action once
        cmd, action = agent(env, obs=0.0, done=False)
        assert (cmd, action) == (BaseEnv.CMD_STEP, 3.5)

        # the consumer times out and re-sends the SAME correlated request,
        # then (after the cached recovery) its next step
        wire.send_message_dealer(dealer, dict(req_a))
        req_b = {"cmd": "step", "action": 7.0}
        wire.stamp_message_id(req_b)
        wire.send_message_dealer(dealer, req_b)
        time.sleep(0.2)

        # frame k+1: reply for A goes out, the duplicate is served from
        # cache (no second simulation), and B is the action applied
        cmd, action = agent(env, obs=3.5, reward=0.35, done=False, time=9)
        assert (cmd, action) == (BaseEnv.CMD_STEP, 7.0)

        first = wire.recv_message_dealer(dealer)
        dup = wire.recv_message_dealer(dealer)
        assert first["obs"] == 3.5 and first["time"] == 9
        assert first[wire.BTMID_KEY] == mid_a
        assert dup == first  # byte-identical cached reply, frame NOT re-run

        # frame k+2: B's reply arrives with B's id — the clock moved once
        cmd, action = agent(env, obs=7.0, reward=0.7, done=False, time=10)
        assert (cmd, action) == (BaseEnv.CMD_STEP, None)
        reply_b = wire.recv_message_dealer(dealer)
        assert reply_b["obs"] == 7.0 and reply_b["time"] == 10
        assert reply_b[wire.BTMID_KEY] == req_b[wire.BTMID_KEY]
    finally:
        agent.close()
        dealer.close(0)


def test_lost_reply_recovered_in_order_without_resimulation(
        fake_blender, monkeypatch):
    """A reply lost on the wire ahead of an out-of-order match is
    RECOVERED, not discarded: the newer reply is held for in-order
    surfacing, the older request is re-sent under its original
    correlation id, and the producer's reply cache answers it without
    simulating the frame twice — every submission still yields exactly
    one transition, in submission order, with a monotonic clock."""
    import zmq

    from blendjax import wire

    counters = EventCounters()
    policy = FaultPolicy(max_retries=2, deadline_s=8.0, backoff_base=0.05,
                         jitter=0.0, circuit_threshold=0, seed=5)
    with launch_env_pool(
        scene="", script=ENV_SCRIPT, num_instances=1, background=True,
        horizon=1_000_000, timeoutms=8000, start_port=13340,
        pipeline_depth=3, fault_policy=policy, counters=counters,
    ) as pool:
        pool.reset()
        pool.step_async([1.0, 2.0, 3.0], indices=[0, 0, 0])

        real_recv = wire.recv_message_dealer
        state = {"swallowed": False}

        def lossy(sock, flags=0):
            d = real_recv(sock, flags=flags)
            if not state["swallowed"]:
                state["swallowed"] = True  # reply 1.0 evaporates in transit
                raise zmq.Again()
            return d

        monkeypatch.setattr("blendjax.wire.recv_message_dealer", lossy)
        idx, obs, rew, done, infos = pool.step_wait(min_ready=3)
        assert state["swallowed"]
        assert list(idx) == [0, 0, 0]
        # submission order held through the loss, and each frame was
        # simulated exactly once (EchoEnv: obs == the action applied)
        assert [float(v) for v in np.asarray(obs)] == [1.0, 2.0, 3.0]
        times = [i["time"] for i in infos]
        assert times == sorted(times) and len(set(times)) == 3
        assert all(i["healthy"] for i in infos)
        assert counters.get("retries") >= 1
        assert counters.get("inflight_discards") == 0
        assert pool.inflight == [0]
        # the channel is still clean: a further round-trip works
        pool.step_async([4.0], indices=[0])
        idx, obs, *_ = pool.step_wait(min_ready=1)
        assert float(np.asarray(obs)[0]) == 4.0


def test_remote_env_policy_retry_is_exactly_once():
    """Consumer-side half of the dedupe: a ``RemoteEnv`` under a
    ``FaultPolicy`` stamps each logical call once, so its timeout-driven
    re-send carries the same correlation id and the agent never
    simulates the retried ``step`` a second time."""
    import threading

    import zmq  # noqa: F401 - transport under test

    from blendjax.btb.env import BaseEnv, RemoteControlledAgent
    from blendjax.btt.env import RemoteEnv
    from helpers.producers import free_port

    addr = f"tcp://127.0.0.1:{free_port()}"
    agent = RemoteControlledAgent(addr, timeoutms=1000)
    policy = FaultPolicy(max_retries=2, backoff_base=0.01, jitter=0.0,
                         circuit_threshold=0, seed=1)
    counters = EventCounters()
    env_ns = types.SimpleNamespace(state=BaseEnv.STATE_RUN)
    result = {}

    def client():
        renv = RemoteEnv(addr, timeoutms=300, fault_policy=policy,
                         counters=counters)
        try:
            result["step"] = renv.step(3.5)
        except BaseException as exc:  # surfaced by the main thread
            result["error"] = exc
        finally:
            renv.close()

    t = threading.Thread(target=client, daemon=True)
    try:
        t.start()
        # serve nothing until the client has timed out and re-sent: both
        # copies of the request are now queued at the producer
        time.sleep(0.45)
        cmd, action = agent(env_ns, obs=0.0, done=False)
        assert (cmd, action) == (BaseEnv.CMD_STEP, 3.5)
        # next frame: the real reply goes out (the client's REQ_CORRELATE
        # drops it as stale), the duplicate is answered from the cache,
        # and NO second 3.5 step is handed to the simulation
        cmd, action = agent(env_ns, obs=3.5, reward=0.35, done=False, time=9)
        assert (cmd, action) == (BaseEnv.CMD_STEP, None)
        t.join(timeout=10)
        assert not t.is_alive()
        assert "error" not in result, result.get("error")
        obs, reward, done, info = result["step"]
        assert (obs, reward, done) == (3.5, 0.35, False)
        assert info["time"] == 9
        assert counters.get("retries") >= 1
    finally:
        agent.close()


def test_legacy_producer_timeout_escalates_without_retry():
    """A producer that does NOT echo ``wire.BTMID_KEY`` gets FIFO reply
    matching, which a retry re-send would permanently shift off by one
    (the legacy producer simulates both copies and the duplicate
    mid-less reply matches the NEXT in-flight record): once the pool has
    seen a mid-less reply from an env, an in-flight timeout escalates
    straight to quarantine instead of re-sending."""
    import threading

    import zmq

    from blendjax import wire
    from helpers.producers import free_port

    addr = f"tcp://127.0.0.1:{free_port()}"
    stall = threading.Event()
    stop = threading.Event()

    def legacy_server():
        ctx = zmq.Context.instance()
        rep = ctx.socket(zmq.REP)
        rep.setsockopt(zmq.LINGER, 0)
        rep.setsockopt(zmq.RCVTIMEO, 100)
        rep.bind(addr)
        t = 0
        try:
            while not stop.is_set():
                try:
                    req = wire.recv_message(rep)
                except zmq.Again:
                    continue
                if stall.is_set():
                    # go silent mid-cycle: the request is consumed, no
                    # reply ever comes
                    stop.wait()
                    break
                t += 1
                # reference-style reply: no BTMID_KEY echo (the first
                # request is the autoreset contract's "reset")
                obs = 0.0 if req["cmd"] == "reset" else req["action"]
                wire.send_message(rep, {
                    "obs": obs, "reward": 0.0, "done": False, "time": t,
                })
        finally:
            rep.close(0)

    thread = threading.Thread(target=legacy_server, daemon=True)
    thread.start()
    counters = EventCounters()
    policy = FaultPolicy(max_retries=3, deadline_s=0.5, backoff_base=0.05,
                         jitter=0.0, circuit_threshold=0, seed=7)
    pool = EnvPool([addr], timeoutms=2000, fault_policy=policy,
                   counters=counters, pipeline_depth=2)
    try:
        pool.step_async([1.0])
        idx, obs, rew, done, infos = pool.step_wait(min_ready=1)
        assert list(idx) == [0] and infos[0]["healthy"]
        assert float(np.asarray(obs)[0]) == 0.0  # the autoreset "reset"

        stall.set()  # the next request will be swallowed, never answered
        pool.step_async([2.0])
        idx, obs, rew, done, infos = pool.step_wait(min_ready=1)
        # escalated to quarantine with ZERO re-sends, despite the policy
        # allowing 3 retries — a retry's duplicate mid-less reply would
        # corrupt FIFO matching for every later transition
        assert counters.get("retries") == 0
        assert counters.get("quarantines") == 1
        assert list(idx) == [0]
        assert bool(np.asarray(done)[0]) and not infos[0]["healthy"]
        assert bool(pool.quarantined[0])
    finally:
        stop.set()
        pool.close()
        thread.join(timeout=3)


def test_legacy_producer_retried_before_first_reply_fails_cleanly():
    """The unknown-echo window: a retry that fires before an env's
    first-ever reply is safe for blendjax producers (dedupe) but not for
    legacy ones — when the late first reply then arrives mid-less, the
    producer may have simulated the frame twice and FIFO attribution is
    unrecoverable, so the env must fail cleanly (quarantine + synthetic
    transitions) instead of serving shifted transitions."""
    import threading

    import zmq

    from blendjax import wire
    from helpers.producers import free_port

    addr = f"tcp://127.0.0.1:{free_port()}"
    stop = threading.Event()

    def slow_legacy_server():
        ctx = zmq.Context.instance()
        rep = ctx.socket(zmq.REP)
        rep.setsockopt(zmq.LINGER, 0)
        rep.setsockopt(zmq.RCVTIMEO, 100)
        rep.bind(addr)
        try:
            while not stop.is_set():
                try:
                    req = wire.recv_message(rep)
                except zmq.Again:
                    continue
                # slower than the policy deadline: the consumer's retry
                # goes out while echo support is still unknown
                time.sleep(1.0)
                obs = 0.0 if req["cmd"] == "reset" else req["action"]
                wire.send_message(rep, {
                    "obs": obs, "reward": 0.0, "done": False, "time": 1,
                })
        finally:
            rep.close(0)

    thread = threading.Thread(target=slow_legacy_server, daemon=True)
    thread.start()
    counters = EventCounters()
    policy = FaultPolicy(max_retries=3, deadline_s=0.4, backoff_base=0.05,
                         jitter=0.0, circuit_threshold=0, seed=7)
    pool = EnvPool([addr], timeoutms=2000, fault_policy=policy,
                   counters=counters, pipeline_depth=2)
    try:
        pool.step_async([1.0])
        pool.step_async([2.0])
        idx, obs, rew, done, infos = pool.step_wait(min_ready=2)
        # the late mid-less first reply arrived AFTER a retry: both
        # submissions resolve synthetically, never as shifted real rows
        assert counters.get("retries") >= 1
        assert counters.get("quarantines") == 1
        assert list(idx) == [0, 0]
        dones = list(np.asarray(done))
        assert dones == [True, False]  # exactly-one quarantine done
        assert not infos[0]["healthy"] and not infos[1]["healthy"]
        assert bool(pool.quarantined[0])
    finally:
        stop.set()
        pool.close()
        thread.join(timeout=3)


def test_vector_env_async_pair(fake_blender):
    """The gymnasium step_async/step_wait pair over a Blender fleet:
    same 5-tuple contract as step(), with the NEXT_STEP autoreset
    boundary crossing the async path."""
    gymnasium = pytest.importorskip("gymnasium")

    from blendjax.btt.vector_env import launch_vector_env

    obs_space = gymnasium.spaces.Box(
        -np.inf, np.inf, shape=(), dtype=np.float64
    )
    act_space = gymnasium.spaces.Box(-10.0, 10.0, shape=(), dtype=np.float64)
    with launch_vector_env(
        scene="", script=ENV_SCRIPT, num_instances=2,
        single_observation_space=obs_space, single_action_space=act_space,
        background=True, horizon=4, timeoutms=30000, start_port=13310,
        pipeline_depth=2,
    ) as env:
        env.reset()
        env.step_async(np.array([1.0, 3.0]))
        obs, rew, term, trunc, info = env.step_wait()
        np.testing.assert_allclose(obs, [1.0, 3.0])
        np.testing.assert_allclose(rew, [0.1, 0.3])
        assert not term.any() and not trunc.any()
        # drive to termination through the async pair
        for _ in range(6):
            env.step_async(np.array([2.0, 2.0]))
            obs, rew, term, trunc, info = env.step_wait()
            if term.any():
                break
        assert term.all()
        # NEXT_STEP autoreset across the pair: fresh obs, zero reward
        env.step_async(np.array([7.0, 7.0]))
        obs, rew, term, trunc, info = env.step_wait()
        np.testing.assert_allclose(obs, [0.0, 0.0])
        np.testing.assert_allclose(rew, [0.0, 0.0])
        assert not term.any()
