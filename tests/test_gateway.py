"""ServeGateway tests (docs/serving.md "ServeGateway").

The load-bearing ones: episode-lease affinity (every step of an episode
lands on the replica that owns its KV-cache row, witnessed by
per-replica seeds), the drain lifecycle, multi-model routing, and the
kill-one-of-three chaos scenario — a SIGKILLed replica respawned by
``FleetWatchdog`` costs its episodes exactly one actionable stale-lease
error before they resume via ``reset()``, with every ACKED request
applied exactly once through the extra hop (the position-sensitive
``LinearModel`` makes a double- or un-applied step visible in every
later prediction).
"""

import time

import numpy as np
import pytest

from blendjax.btt.faults import FaultPolicy
from blendjax.utils.timing import (
    GATEWAY_EVENTS,
    GATEWAY_STAGES,
    EventCounters,
    StageTimer,
)


def _gateway_counts(counters):
    return {k: v for k, v in counters.snapshot().items()
            if k.startswith("gateway_")}


def _two_replicas(seeds=(0, 7), slots=8, obs_dim=4):
    """Two in-thread linear servers with DIFFERENT seeds: predictions
    witness which replica served an episode."""
    from blendjax.serve import LinearModel, start_server_thread

    handles = [
        start_server_thread(
            LinearModel(obs_dim=obs_dim, slots=slots, seed=s),
            counters=EventCounters(),
        )
        for s in seeds
    ]
    return handles


def _ref_w(seed, obs_dim=4):
    from blendjax.serve import LinearModel

    return LinearModel(obs_dim=obs_dim, slots=1, seed=seed).w


# ---------------------------------------------------------------------------
# routing: affinity, spread, drain
# ---------------------------------------------------------------------------


def test_gateway_lease_affinity_and_replica_stamp():
    """Every step of one episode is served by ONE replica (its
    predictions stay consistent with a single weight matrix and a
    monotonically increasing position), the reply carries the serving
    replica's id, and the affinity counter pins the routing path."""
    from blendjax.serve import ServeClient
    from blendjax.serve.gateway import start_gateway_thread

    handles = _two_replicas()
    counters = EventCounters()
    obs = np.arange(4, dtype=np.float32)
    ws = {"r0": _ref_w(0), "r1": _ref_w(7)}
    try:
        with start_gateway_thread(
            [h.address for h in handles], counters=counters,
            timer=StageTimer(), scrape_interval_s=0.1,
        ) as gw:
            clients = [ServeClient(gw.address, timeoutms=5000)
                       for _ in range(4)]
            for c in clients:
                c.reset()
            for k in range(3):
                for c in clients:
                    r = c.step(obs)
                    assert r["replica"] in ws
                    assert c.replica == r["replica"]
                    assert r["pos"] == k
                    np.testing.assert_allclose(
                        r["pred"],
                        obs @ ws[r["replica"]] + np.float32(k),
                    )
            snap = _gateway_counts(counters)
            assert snap["gateway_routed"] >= 16  # 4 resets + 12 steps
            assert snap["gateway_affinity_hits"] >= 12
            hello = clients[0].hello()
            assert hello["gateway"] is True
            assert set(hello["replicas"]) == {"r0", "r1"}
            # once a scrape lands, the gateway hello merges a healthy
            # replica's PR-10 capability fields, so hello consumers
            # written against a bare server work unchanged
            deadline = time.monotonic() + 5
            while "obs_dim" not in hello:
                assert time.monotonic() < deadline, hello
                time.sleep(0.02)
                hello = clients[0].hello()
            assert hello["obs_dim"] == 4
            assert hello["max_batch"] > 0
            for c in clients:
                c.close_episode()
                c.close()
    finally:
        for h in handles:
            h.close()


def test_gateway_spreads_fresh_episodes_across_replicas():
    from blendjax.serve import ServeClient
    from blendjax.serve.gateway import start_gateway_thread

    handles = _two_replicas(seeds=(0, 0))
    try:
        with start_gateway_thread(
            [h.address for h in handles], counters=EventCounters(),
            scrape_interval_s=0.1,
        ) as gw:
            clients = [ServeClient(gw.address, timeoutms=5000)
                       for _ in range(6)]
            for c in clients:
                c.reset()
            # the optimistic pending-live estimate spreads a reset
            # burst even before any scrape lands
            per_replica = [
                h.server.counters.get("serve_resets") for h in handles
            ]
            assert all(n > 0 for n in per_replica), per_replica
            for c in clients:
                c.close_episode()
                c.close()
    finally:
        for h in handles:
            h.close()


def test_gateway_drain_lifecycle():
    """A draining replica receives no fresh episodes but finishes its
    live ones; undrain restores it; the RPC admin surface mirrors the
    method one."""
    from blendjax.serve import ServeClient
    from blendjax.serve.gateway import start_gateway_thread

    handles = _two_replicas(seeds=(0, 0))
    counters = EventCounters()
    obs = np.zeros(4, np.float32)
    try:
        with start_gateway_thread(
            [h.address for h in handles], counters=counters,
            scrape_interval_s=0.1,
        ) as gw:
            live = ServeClient(gw.address, timeoutms=5000)
            live.reset()
            live.step(obs)
            victim = live.replica
            gw.gateway.drain(victim)
            vic_counters = handles[int(victim[1:])].server.counters
            resets_before = vic_counters.get("serve_resets")
            others = [ServeClient(gw.address, timeoutms=5000)
                      for _ in range(4)]
            for c in others:
                c.reset()
            assert vic_counters.get("serve_resets") == resets_before
            # the drained replica still serves its live episode
            assert live.step(obs)["replica"] == victim
            # undrain via the RPC admin surface; fresh episodes return
            admin = ServeClient(gw.address, timeoutms=5000)
            reply = admin.rpc("undrain", {"replica": victim})
            assert reply["draining"] == []
            assert _gateway_counts(counters)["gateway_drains"] == 1
            # draining every replica makes a fresh reset fail actionably
            for rid in ("r0", "r1"):
                admin.rpc("drain", {"replica": rid})
            denied = ServeClient(
                gw.address, timeoutms=5000,
                fault_policy=FaultPolicy(max_retries=0),
            )
            with pytest.raises(RuntimeError, match="no healthy replica"):
                denied.reset()
            for c in others + [live, admin, denied]:
                c.close()
    finally:
        for h in handles:
            h.close()


# ---------------------------------------------------------------------------
# multi-model routing through the gateway
# ---------------------------------------------------------------------------


def test_gateway_routes_by_model_id():
    """Replicas hosting different model ids: a client pinned to model
    "b" is served by the replica hosting it (seed witness), and an
    unhosted model id errors actionably."""
    from blendjax.serve import LinearModel, ServeClient, start_server_thread
    from blendjax.serve.gateway import start_gateway_thread

    obs = np.arange(4, dtype=np.float32)
    ha = start_server_thread(
        {"a": LinearModel(obs_dim=4, slots=4, seed=0)},
        counters=EventCounters(),
    )
    hb = start_server_thread(
        {"b": LinearModel(obs_dim=4, slots=4, seed=7)},
        counters=EventCounters(),
    )
    try:
        with start_gateway_thread(
            [ha.address, hb.address], counters=EventCounters(),
            scrape_interval_s=0.05,
        ) as gw:
            # wait for the model map to be learned from the scrape
            deadline = time.monotonic() + 5
            cb = ServeClient(gw.address, model="b", timeoutms=5000)
            while time.monotonic() < deadline:
                hello = cb.hello()
                if set(hello["models"]) == {"a", "b"}:
                    break
                time.sleep(0.02)
            cb.reset()
            r = cb.step(obs)
            assert r["replica"] == "r1"
            np.testing.assert_allclose(r["pred"], obs @ _ref_w(7))
            bogus = ServeClient(
                gw.address, model="zzz", timeoutms=5000,
                fault_policy=FaultPolicy(max_retries=0),
            )
            with pytest.raises(RuntimeError, match="zzz"):
                bogus.reset()
            cb.close_episode()
            cb.close()
            bogus.close()
    finally:
        ha.close()
        hb.close()


# ---------------------------------------------------------------------------
# lease errors, prefill through the hop
# ---------------------------------------------------------------------------


def test_gateway_unknown_lease_errors_and_noop_close():
    from blendjax.serve import ServeClient
    from blendjax.serve.gateway import start_gateway_thread

    handles = _two_replicas()
    counters = EventCounters()
    try:
        with start_gateway_thread(
            [h.address for h in handles], counters=counters,
        ) as gw:
            c = ServeClient(gw.address, timeoutms=5000,
                            fault_policy=FaultPolicy(max_retries=0))
            c.slot, c.episode = 0, 424242  # never admitted
            with pytest.raises(RuntimeError,
                               match="reset\\(\\) and resume"):
                c.step(np.zeros(4, np.float32))
            # a stale close is answered, never an error (the server's
            # own no-op close semantics through the hop)
            c.slot, c.episode = 0, 424242
            assert not c.close_episode()
            assert _gateway_counts(
                counters
            )["gateway_stale_lease_redirects"] >= 1
            c.close()
    finally:
        for h in handles:
            h.close()


def test_gateway_prefill_admission_end_to_end():
    """reset(prefix=...) rides the hop: the lease comes back rewritten,
    the prefill prediction matches T serial steps, and the episode
    continues at position T on the SAME replica."""
    from blendjax.serve import ServeClient
    from blendjax.serve.gateway import start_gateway_thread

    handles = _two_replicas(seeds=(3, 3))
    w = _ref_w(3)
    rng = np.random.default_rng(5)
    prefix = rng.standard_normal((6, 4)).astype(np.float32)
    obs = rng.standard_normal(4).astype(np.float32)
    try:
        with start_gateway_thread(
            [h.address for h in handles], counters=EventCounters(),
        ) as gw:
            c = ServeClient(gw.address, timeoutms=5000)
            reply = c.reset(prefix=prefix)
            assert reply["pos"] == 6
            np.testing.assert_allclose(
                reply["pred"], prefix[-1] @ w + np.float32(5)
            )
            r = c.step(obs)
            assert r["pos"] == 6
            assert r["replica"] == reply["replica"]
            np.testing.assert_allclose(r["pred"], obs @ w + np.float32(6))
            c.close_episode()
            c.close()
    finally:
        for h in handles:
            h.close()


# ---------------------------------------------------------------------------
# telemetry plane + client diagnosability
# ---------------------------------------------------------------------------


def test_gateway_is_a_scrapeable_hub_remote():
    from blendjax.obs.hub import TelemetryHub
    from blendjax.serve import ServeClient
    from blendjax.serve.gateway import start_gateway_thread

    handles = _two_replicas()
    counters, timer = EventCounters(), StageTimer()
    try:
        with start_gateway_thread(
            [h.address for h in handles], counters=counters, timer=timer,
        ) as gw:
            c = ServeClient(gw.address, timeoutms=5000)
            c.reset()
            for _ in range(3):
                c.step(np.zeros(4, np.float32))
            hub = TelemetryHub()
            c.register_with_hub(hub, "gateway")
            snap = hub.scrape()
            assert snap["counters"]["gateway_routed"] >= 4
            assert snap["counters"]["gateway_affinity_hits"] >= 3
            # zero-fill: every gateway counter AND stage is present
            for name in GATEWAY_EVENTS:
                assert name in snap["counters"], name
            for stage in GATEWAY_STAGES:
                assert stage in snap["stages"], stage
            assert snap["stages"]["gw_route"]["count"] >= 4
            assert snap["stages"]["gw_reply"]["p99_ms"] >= 0.0
            c.close()
    finally:
        for h in handles:
            h.close()


def test_client_surfaces_replica_id_in_error_and_spans():
    """The small-fix satellite: after serving through a gateway, the
    client knows which replica answered last — a transport failure's
    ServeRPCError text names it, and the client RPC spans carry it."""
    from blendjax.obs.spans import SpanRecorder
    from blendjax.serve import ServeClient, ServeRPCError
    from blendjax.serve.gateway import start_gateway_thread

    handles = _two_replicas(seeds=(0, 0))
    rec = SpanRecorder()
    gw = start_gateway_thread(
        [h.address for h in handles], counters=EventCounters(),
    )
    try:
        c = ServeClient(
            gw.address, timeoutms=300, span_recorder=rec,
            fault_policy=FaultPolicy(max_retries=0, circuit_threshold=0),
        )
        c.reset()
        c.step(np.zeros(4, np.float32))
        assert c.replica in ("r0", "r1")
        served_by = c.replica
        spans = rec.drain()
        stamped = [s for s in spans
                   if (s.get("args") or {}).get("replica") == served_by]
        assert stamped, spans
        # kill the gateway: the next RPC times out and the error text
        # names the last replica that served this client
        gw.close()
        gw = None
        with pytest.raises(ServeRPCError, match=served_by):
            c.step(np.zeros(4, np.float32))
        c.close()
    finally:
        if gw is not None:
            gw.close()
        for h in handles:
            h.close()


# ---------------------------------------------------------------------------
# exactly-once through the extra hop (chaos)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
@pytest.mark.parametrize("transport", ["tcp", "shm"])
def test_exactly_once_through_gateway_with_wire_faults(transport):
    """Wire faults between client and GATEWAY: dropped replies and
    duplicated requests across the two-hop path still yield exactly one
    applied step per submitted request — the gateway forwards BTMID
    verbatim, re-forwards in-flight retries to the SAME replica, and
    answers executed retries from its own reply cache.  Parametrized
    over both wires (ISSUE-12): ``tcp`` injects at the TCP chunk layer
    (ChaosProxy, shm pinned off), ``shm`` at the ring frame layer
    (ShmChaos) on the client->gateway hop — with the gateway->replica
    hop ALSO riding its own shm channel."""
    from blendjax.btt.chaos import ChaosProxy
    from blendjax.btt.shm_rpc import ShmChaos, enabled
    from blendjax.serve import LinearModel, ServeClient, start_server_thread
    from blendjax.serve.gateway import start_gateway_thread

    if transport == "shm" and not enabled():
        pytest.skip("shm rpc unavailable on this host")
    counters = EventCounters()
    obs = np.arange(4, dtype=np.float32)
    ref = LinearModel(obs_dim=4, slots=2, seed=0)
    ref.reset_rows(np.asarray([0]))
    h = start_server_thread(
        LinearModel(obs_dim=4, slots=2, seed=0), counters=EventCounters()
    )
    proxy = None
    chaos = None
    try:
        with start_gateway_thread(
            [h.address], counters=counters, scrape_interval_s=0.1
        ) as gw:
            if transport == "tcp":
                proxy = ChaosProxy(gw.address)
                client = ServeClient(
                    proxy.address,
                    fault_policy=FaultPolicy(
                        max_retries=4, backoff_base=0.02,
                        backoff_max=0.1, circuit_threshold=0, seed=1,
                    ),
                    counters=counters, timeoutms=400, shm=False,
                )
            else:
                chaos = ShmChaos(seed=1)
                client = ServeClient(
                    gw.address,
                    fault_policy=FaultPolicy(
                        max_retries=4, backoff_base=0.02,
                        backoff_max=0.1, circuit_threshold=0, seed=1,
                    ),
                    counters=counters, timeoutms=400, shm_chaos=chaos,
                )
            client.reset()
            preds = []
            for t in range(16):
                if t == 4:
                    if proxy is not None:
                        proxy.drop_next("down")  # lose a reply -> retry
                    else:
                        assert client.transport == "shm", \
                            "client->gateway upgrade never happened"
                        chaos.drop_next("down")
                if t == 9:
                    (proxy or chaos).dup_next("up")  # duplicate request
                preds.append(client.step(obs)["pred"])
            want = [ref.step_rows(np.asarray([0]), obs[None])[0]
                    for _ in range(16)]
            np.testing.assert_allclose(np.stack(preds),
                                       np.stack(want))
            snap = counters.snapshot()
            assert snap.get("retries", 0) >= 1
            # the retry was healed on the gateway/replica side, not
            # by accident: a cache hit or an in-flight re-forward
            assert (
                snap.get("gateway_cache_hits", 0)
                + snap.get("gateway_dup_inflight", 0)
            ) >= 1, snap
            if transport == "shm":
                # the gateway->replica hop negotiated its own channel
                # off the scrape cycle: the step traffic moved bytes
                # through the replica's shm transport
                deadline = time.monotonic() + 5
                while time.monotonic() < deadline:
                    if any(r.shm is not None
                           for r in gw.gateway._replicas.values()):
                        break
                    time.sleep(0.05)
                assert any(r.shm is not None
                           for r in gw.gateway._replicas.values()), \
                    "gateway->replica hop never upgraded"
            client.close()
    finally:
        if proxy is not None:
            proxy.close()
        h.close()


@pytest.mark.chaos
def test_kill_one_replica_of_three_respawn_exactly_once():
    """THE fleet chaos contract (ISSUE-11): SIGKILL 1 of 3 replica
    processes mid-traffic; ``FleetWatchdog(restart=True)`` respawns it;
    clients behind the gateway observe only timeouts and ONE actionable
    stale-lease/unknown-slot error each, then resume after ``reset()``
    — and every ACKED request was applied exactly once (each acked
    prediction equals ``obs @ W + k`` where k counts the acks since the
    episode's reset; a double- or un-applied step would shift every
    later position).  Fault + gateway counters pinned."""
    from blendjax.btt.chaos import kill_instance
    from blendjax.btt.watchdog import FleetWatchdog
    from blendjax.serve import ServeClient, ServerFleet
    from blendjax.serve.gateway import start_gateway_thread

    gw_counters = EventCounters()
    obs = np.arange(4, dtype=np.float32)
    w = _ref_w(0)
    with ServerFleet(3, model="linear", obs_dim=4, slots=8) as fleet:
        gw = start_gateway_thread(
            fleet.addresses, counters=gw_counters, scrape_interval_s=0.15
        )
        wd = FleetWatchdog(
            fleet, interval=0.2, restart=True,
            on_death=gw.gateway.notify_replica_death,
            on_respawn=gw.gateway.notify_replica_respawn,
        )
        try:
            with wd:
                clients = []
                for i in range(4):
                    c = ServeClient(
                        gw.address, timeoutms=400,
                        fault_policy=FaultPolicy(
                            max_retries=1, backoff_base=0.05,
                            backoff_max=0.2, circuit_threshold=0,
                            seed=i,
                        ),
                        counters=EventCounters(),
                    )
                    c.reset()
                    clients.append(c)
                acked = [0] * len(clients)

                def acked_step(i):
                    """One step; on ack, verify exactly-once and count."""
                    r = clients[i].step(obs)
                    np.testing.assert_allclose(
                        r["pred"], obs @ w + np.float32(acked[i])
                    )
                    acked[i] += 1

                for i in range(len(clients)):
                    acked_step(i)
                # kill the replica that owns clients[1]'s episode, so a
                # client deterministically crosses the stale-lease path
                victim = int(clients[1].replica[1:])
                kill_instance(fleet, victim)
                # drive traffic through the outage: timeouts retry the
                # step; the actionable lease error resets the episode
                stale_errors = 0
                for i, c in enumerate(clients):
                    deadline = time.monotonic() + 30
                    done = 0
                    while time.monotonic() < deadline and done < 3:
                        try:
                            acked_step(i)
                            done += 1
                        except TimeoutError:
                            continue
                        except RuntimeError as exc:
                            assert "reset() and resume" in str(exc), exc
                            stale_errors += 1
                            while time.monotonic() < deadline:
                                try:
                                    c.reset(timeout_ms=800)
                                    acked[i] = 0
                                    break
                                except (TimeoutError, RuntimeError):
                                    time.sleep(0.1)
                    assert done == 3, f"client {i} never recovered"
                # at least the victim's client crossed the stale path
                assert stale_errors >= 1
                # let the respawn scrape land, then pin the counters
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    snap = _gateway_counts(gw_counters)
                    if snap.get("gateway_replica_respawns", 0) >= 1:
                        break
                    time.sleep(0.1)
                assert snap.get("gateway_replica_quarantined", 0) >= 1
                assert snap.get("gateway_replica_respawns", 0) >= 1
                assert snap.get("gateway_stale_lease_redirects", 0) >= 1
                assert wd.deaths and wd.deaths[-1][2]  # restarted
                # all three replicas alive behind the gateway again
                assert wd.alive == 3
                for c in clients:
                    c.close()
        finally:
            gw.close()
    # no leaked /dev/shm objects (ISSUE-12): the SIGKILLed replica ran
    # no cleanup, but the respawn path swept its generation and fleet
    # teardown swept the rest — rings, bells, client-side halves
    from blendjax.btt.shm_rpc import leaked_objects

    for p in fleet._procs:
        if p.shm_base is not None:
            assert not leaked_objects(p.shm_base), leaked_objects(
                p.shm_base
            )


# ---------------------------------------------------------------------------
# sharded data plane chaos (docs/serving.md "The sharded gateway")
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_kill_one_gateway_worker_exactly_once():
    """THE sharded-gateway chaos contract (ISSUE-16): SIGKILL 1 of 3
    gateway WORKER processes mid-traffic.  Clients direct-dialed onto
    the survivors keep stepping with ZERO errors — their traffic never
    touches the dead worker or the front.  The victim's clients observe
    only timeouts (the dead direct dial), then fall back to the front,
    which answers their stale partition with the ONE actionable
    stale-lease error (``reset() and resume``); after ``reset()`` they
    land on a live worker and every ACKED request was applied exactly
    once (the position witness: each acked prediction equals
    ``obs @ W + k`` with k the acks since that episode's reset — a
    double- or un-applied step shifts every later position).  The
    watchdog respawns the victim under its parent-pinned address and
    shm base; counters pin deaths, respawns and the stale-lease path;
    no ``/dev/shm`` leak survives the close."""
    from blendjax.btt.chaos import kill_instance
    from blendjax.serve import ServeClient, ServerFleet
    from blendjax.serve.gateway import start_sharded_gateway_thread

    gw_counters = EventCounters()
    obs = np.arange(4, dtype=np.float32)
    w = _ref_w(0)
    with ServerFleet(2, model="linear", obs_dim=4, slots=16) as fleet:
        gw = start_sharded_gateway_thread(
            fleet.addresses, workers=3, counters=gw_counters,
            scrape_interval_s=0.15, watchdog_interval_s=0.2,
        )
        bases = list(gw.gateway._wbases)
        try:
            clients, acked = [], []

            def admit():
                c = ServeClient(
                    gw.address, timeoutms=600,
                    fault_policy=FaultPolicy(
                        max_retries=1, backoff_base=0.05,
                        backoff_max=0.2, circuit_threshold=0,
                        seed=len(clients),
                    ),
                    counters=EventCounters(),
                )
                c.reset()
                clients.append(c)
                acked.append(0)

            for _ in range(6):
                admit()
            # fresh traffic hashes by correlation id: with 6 episodes
            # the workers are almost surely not all the same, but the
            # test must not depend on hash luck — admit a few more
            # until the victim's partition AND a survivor both exist
            while (len({c.gw_worker for c in clients}) < 2
                   and len(clients) < 12):
                admit()
            tags = {c.gw_worker for c in clients}
            assert len(tags) >= 2, tags

            def acked_step(i):
                r = clients[i].step(obs)
                np.testing.assert_allclose(
                    r["pred"], obs @ w + np.float32(acked[i])
                )
                acked[i] += 1

            for i in range(len(clients)):
                acked_step(i)
                acked_step(i)
            victim_tag = clients[0].gw_worker
            survivors = [i for i, c in enumerate(clients)
                         if c.gw_worker != victim_tag]
            on_victim = [i for i, c in enumerate(clients)
                         if c.gw_worker == victim_tag]
            kill_instance(gw.gateway, int(victim_tag[2:]))
            # drive traffic through the outage: survivors must not see
            # a single error; the victim's clients ride timeouts ->
            # front fallback -> ONE stale-lease error -> reset -> resume
            stale_errors, survivor_errors = 0, 0
            for i in range(len(clients)):
                deadline = time.monotonic() + 30
                done = 0
                while time.monotonic() < deadline and done < 3:
                    try:
                        acked_step(i)
                        done += 1
                    except TimeoutError:
                        if i in survivors:
                            survivor_errors += 1
                        continue
                    except RuntimeError as exc:
                        assert "reset() and resume" in str(exc), exc
                        if i in survivors:
                            survivor_errors += 1
                        stale_errors += 1
                        while time.monotonic() < deadline:
                            try:
                                clients[i].reset(timeout_ms=800)
                                acked[i] = 0
                                break
                            except (TimeoutError, RuntimeError):
                                time.sleep(0.1)
                assert done == 3, f"client {i} never recovered"
            assert survivor_errors == 0
            assert stale_errors >= 1
            assert on_victim  # the stale path was actually exercised
            # the respawn rejoined under its pinned identity: wait for
            # its first answered scrape, then pin the counters
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                snap = _gateway_counts(gw_counters)
                if (snap.get("gateway_worker_respawns", 0) >= 1
                        and all(x.alive for x in gw.gateway._workers)):
                    break
                time.sleep(0.1)
            assert snap.get("gateway_worker_deaths", 0) >= 1, snap
            assert snap.get("gateway_worker_respawns", 0) >= 1, snap
            assert all(x.alive for x in gw.gateway._workers)
            # the actionable error came off the stale partition: the
            # front's dead-worker answer (gateway_lease_rehash) or the
            # respawned worker's unknown-lease answer — the merged
            # fleet view carries both, but a worker-side increment only
            # reaches it on the NEXT answered scrape, so wait one out
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                merged = gw.gateway.gateway_counters()
                if merged.get("gateway_stale_lease_redirects", 0) >= 1:
                    break
                time.sleep(0.1)
            assert merged.get("gateway_stale_lease_redirects", 0) >= 1, \
                merged
            for c in clients:
                c.close()
        finally:
            gw.close()
    # PR-12 hygiene through the sharded plane: the SIGKILLed worker ran
    # no cleanup, but its parent-pinned base prefix was swept before
    # the respawn and again at close
    from blendjax.btt.shm_rpc import leaked_objects

    for base in bases:
        if base is not None:
            assert not leaked_objects(base), leaked_objects(base)


@pytest.mark.chaos
def test_exactly_once_through_sharded_front_with_wire_faults():
    """Wire faults between client and the SHARDED front (ChaosProxy:
    dropped replies, duplicated requests) still yield exactly one
    applied step per submitted request.  The client is pinned to the
    front (``follow_redirects=False``) so every message rides the
    relay path: the front re-forwards a same-mid retry to the SAME
    worker (route cache), and the worker's dedupe/reply cache answers
    executed retries — the front itself holds no reply cache."""
    from blendjax.btt.chaos import ChaosProxy
    from blendjax.serve import LinearModel, ServeClient, start_server_thread
    from blendjax.serve.gateway import start_sharded_gateway_thread

    counters = EventCounters()
    obs = np.arange(4, dtype=np.float32)
    ref = LinearModel(obs_dim=4, slots=2, seed=0)
    ref.reset_rows(np.asarray([0]))
    h = start_server_thread(
        LinearModel(obs_dim=4, slots=2, seed=0), counters=EventCounters()
    )
    proxy = None
    try:
        with start_sharded_gateway_thread(
            [h.address], workers=2, counters=counters,
            scrape_interval_s=0.1, supervise=False,
        ) as gw:
            proxy = ChaosProxy(gw.address)
            client = ServeClient(
                proxy.address,
                fault_policy=FaultPolicy(
                    max_retries=4, backoff_base=0.02,
                    backoff_max=0.1, circuit_threshold=0, seed=1,
                ),
                counters=counters, timeoutms=600, shm=False,
                follow_redirects=False,
            )
            client.reset()
            preds = []
            for t in range(16):
                if t == 4:
                    proxy.drop_next("down")  # lose a reply -> retry
                if t == 9:
                    proxy.dup_next("up")     # duplicate a request
                preds.append(client.step(obs)["pred"])
            want = [ref.step_rows(np.asarray([0]), obs[None])[0]
                    for _ in range(16)]
            np.testing.assert_allclose(np.stack(preds), np.stack(want))
            snap = counters.snapshot()
            assert snap.get("retries", 0) >= 1
            assert snap.get("gateway_front_relays", 0) >= 16
            # the retry was healed on the worker side, not by accident:
            # its dedupe or reply cache fired.  Worker counters reach
            # the front on the scrape cycle — wait one out
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                merged = gw.gateway.gateway_counters()
                if (merged.get("gateway_cache_hits", 0)
                        + merged.get("gateway_dup_inflight", 0)) >= 1:
                    break
                time.sleep(0.05)
            assert (
                merged.get("gateway_cache_hits", 0)
                + merged.get("gateway_dup_inflight", 0)
            ) >= 1, merged
            client.close()
    finally:
        if proxy is not None:
            proxy.close()
        h.close()


# ---------------------------------------------------------------------------
# bench schema + headline carry (satellites)
# ---------------------------------------------------------------------------


def test_gateway_bench_emits_locked_schema():
    from benchmarks._common import GATEWAY_BENCH_KEYS
    from benchmarks.serve_benchmark import measure_gateway

    rec = measure_gateway(seconds=1.6, clients=4, replicas=2,
                          work_us=100, rounds=1)
    assert all(k in rec for k in GATEWAY_BENCH_KEYS), [
        k for k in GATEWAY_BENCH_KEYS if k not in rec
    ]
    assert rec["gateway_qps"] > 0
    assert rec["gateway_qps_1replica"] > 0
    assert rec["gateway_scale_x"] is not None
    assert rec["gateway_p99_ms"] >= rec["gateway_p50_ms"]
    for stage in GATEWAY_STAGES:
        assert stage in rec["stages"], stage
    assert rec["gateway_counters"].get("gateway_drains", 0) >= 1
    # 1-worker mode: the shard-phase keys ride as None, never missing
    assert rec["gateway_workers"] == 1
    assert rec["gateway_qps_1worker"] is None
    assert rec["gateway_qps_nworker"] is None
    assert rec["gateway_shard_x"] is None
    assert rec["shard_profile"] is None


@pytest.mark.chaos
def test_sharded_gateway_bench_emits_shard_phase():
    """``--gateway-workers 2`` adds the shard phase: same locked
    schema, with the 1-worker/N-worker pair, its ratio and the
    shard-phase fleet profile populated (docs/serving.md)."""
    from benchmarks._common import GATEWAY_BENCH_KEYS
    from benchmarks.serve_benchmark import measure_gateway

    rec = measure_gateway(seconds=2.4, clients=4, replicas=2,
                          work_us=100, rounds=1, gateway_workers=2,
                          shard_work_us=50, shard_obs_dim=16,
                          shard_clients=4)
    assert all(k in rec for k in GATEWAY_BENCH_KEYS), [
        k for k in GATEWAY_BENCH_KEYS if k not in rec
    ]
    assert rec["gateway_workers"] == 2
    assert rec["gateway_qps"] > 0
    assert rec["gateway_qps_1worker"] > 0
    assert rec["gateway_qps_nworker"] > 0
    assert rec["gateway_shard_x"] is not None
    assert len(rec["shard_pair_ratios"]) == 1
    assert rec["shard_profile"] == {
        "work_us": 50, "obs_dim": 16, "clients": 4,
    }
    # the sharded plane's lifecycle showed up in the merged counters
    assert rec["gateway_counters"].get("gateway_front_relays", 0) >= 1


def test_bench_headline_carries_gateway_metrics():
    import json

    import bench

    gb = {
        "phase": "gateway_bench", "replicas": 3, "clients": 16,
        "work_us": 2000, "rounds": 3, "window_s": 2.5,
        "gateway_qps": 834.0, "gateway_qps_1replica": 372.0,
        "gateway_p50_ms": 18.0, "gateway_p99_ms": 47.1,
        "gateway_scale_x": 2.24, "pair_ratios": [2.2, 2.3],
        "gateway_workers": 2, "gateway_qps_1worker": 610.0,
        "gateway_qps_nworker": 845.0, "gateway_shard_x": 1.39,
        "shard_pair_ratios": [1.3, 1.4],
        "shard_profile": {"work_us": 500, "obs_dim": 128,
                          "clients": 12},
        "gateway_counters": {}, "stages": {},
    }
    sb = {
        "phase": "serve_bench", "model": "seqformer", "clients": 8,
        "serve_qps": 2650.0, "serve_p50_ms": 2.4, "serve_p99_ms": 6.4,
        "serve_batch_x": 3.1, "serve_int8_x": 0.98,
        "serve_prefill_x": 14.9,
        "serve_qps_modes": {}, "stages": {},
    }
    out = bench.assemble({}, host_fallback=lambda: 1.0, serve_bench=sb,
                         gateway_bench=gb)
    assert out["gateway_bench"]["gateway_scale_x"] == 2.24
    assert out["gateway_bench"]["gateway_shard_x"] == 1.39
    assert out["serve_bench"]["serve_prefill_x"] == 14.9
    line = bench.headline(out)
    assert line["gateway_qps"] == 834.0
    assert line["gateway_shard_x"] == 1.39
    assert len(json.dumps(line).encode()) <= bench.HEADLINE_BYTE_BUDGET
    assert line["gateway_p99_ms"] == 47.1
    assert line["gateway_scale_x"] == 2.24
    assert line["serve_prefill_x"] == 14.9
    assert len(json.dumps(line)) + 1 <= bench.HEADLINE_BYTE_BUDGET
