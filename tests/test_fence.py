"""blendjax.utils.fence: value fences, streaming fence chains, and the
block_until_ready self-check (the round-4 phantom-fence productization)."""

import time

import jax
import jax.numpy as jnp
import numpy as np

from blendjax.utils import fence_chain, fences_valid, value_fence


def test_value_fence_returns_checksum_and_blocks():
    tree = {"a": jnp.ones((4, 4)), "b": {"c": jnp.full((2,), 2.0)}}
    v = value_fence(tree)
    assert v == 3.0  # mean(a)=1 + mean(c)=2
    assert value_fence({"x": []}) == 0.0
    assert value_fence([1.0, None]) == 0.0  # non-array leaves ignored


def test_fence_chain_folds_and_syncs():
    chain = fence_chain()
    f = jax.jit(lambda x: x * 2)
    total = 0.0
    for i in range(5):
        y = f(jnp.full((3,), float(i)))
        chain.fold(y)
        total += 2.0 * i
    assert chain.sync() == total
    # sync is idempotent and reflects further folds
    chain.fold(jnp.full((2,), 1.0))
    assert chain.sync() == total + 1.0


def test_fence_chain_fences_dispatched_work():
    """After sync(), a dispatched computation's effects are observable at
    host speed (the fetch already waited)."""
    chain = fence_chain()
    big = jax.jit(lambda x: jnp.sin(x).sum())(jnp.ones((256, 256)))
    chain.fold(big)
    chain.sync()
    t0 = time.perf_counter()
    np.asarray(big)  # already done: near-instant
    assert time.perf_counter() - t0 < 0.5


def test_fences_valid_on_cpu():
    """CPU's block_until_ready is a real fence, so an absurd claimed peak
    flags it and a generous peak clears it."""
    ok, details = fences_valid(peak_flops_per_sec=1e18, n=256)
    assert ok, details
    ok, details = fences_valid(peak_flops_per_sec=1.0, n=256)
    assert not ok  # any real compute beats a 1 FLOP/s "peak"
