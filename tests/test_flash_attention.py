"""Pallas flash attention vs the reference einsum attention — forward
and gradient parity in interpret mode (same kernel code CI can run on
CPU), plus SeqFormer integration through the ``attn_fn`` seam."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blendjax.ops.flash_attention import flash_attention, make_flash_attention
from blendjax.parallel.ring_attention import full_attention


def _qkv(b=2, t=256, h=4, d=64, dtype=jnp.float32, seed=0):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (
        jax.random.normal(k1, (b, t, h, d), dtype),
        jax.random.normal(k2, (b, t, h, d), dtype),
        jax.random.normal(k3, (b, t, h, d), dtype),
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("blocks", [(128, 128), (64, 128), (128, 64)])
def test_forward_matches_reference(causal, blocks):
    q, k, v = _qkv()
    bq, bkv = blocks
    out = flash_attention(q, k, v, causal, None, bq, bkv, True)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_bfloat16_io():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, True, None, 128, 128, True)
    assert out.dtype == jnp.bfloat16
    ref = full_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        causal=True,
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=2e-2, rtol=2e-2
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("blocks", [(64, 64), (32, 64), (64, 32)])
def test_gradients_match_reference(causal, blocks):
    q, k, v = _qkv(t=128, d=32)
    bq, bkv = blocks

    def loss_flash(q, k, v):
        return (
            flash_attention(q, k, v, causal, None, bq, bkv, True) ** 2
        ).sum()

    def loss_ref(q, k, v):
        return (full_attention(q, k, v, causal=causal) ** 2).sum()

    gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5
        )


def test_gradients_explicit_scale_and_bf16():
    q, k, v = _qkv(t=128, d=32, dtype=jnp.bfloat16)

    def loss_flash(q, k, v):
        return (
            flash_attention(q, k, v, True, 0.25, 64, 64, True)
            .astype(jnp.float32) ** 2
        ).sum()

    def loss_ref(q, k, v):
        # f32-math baseline: the kernel computes in f32 internally, while
        # a bf16 einsum reference would carry its own rounding error
        return (
            full_attention(
                q.astype(jnp.float32), k.astype(jnp.float32),
                v.astype(jnp.float32), causal=True, scale=0.25,
            ) ** 2
        ).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
    )
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=5e-2, rtol=5e-2,
        )


def test_seqformer_attn_fn_integration():
    """The kernel slots into the SeqFormer through the attn_fn seam and
    reproduces the default-attention forward exactly."""
    from blendjax.models import seqformer

    params = seqformer.init(
        jax.random.PRNGKey(0), obs_dim=6, d_model=32, n_heads=2,
        n_layers=2, max_len=128,
    )
    obs = jax.random.normal(jax.random.PRNGKey(1), (2, 128, 6), jnp.float32)
    default = seqformer.apply(params, obs, compute_dtype=jnp.float32)
    flash = seqformer.apply(
        params, obs, compute_dtype=jnp.float32,
        attn_fn=make_flash_attention(causal=True, block_q=64, block_kv=64,
                                     interpret=True),
    )
    np.testing.assert_allclose(
        np.asarray(flash), np.asarray(default), atol=2e-4, rtol=2e-4
    )


@pytest.mark.parametrize("window", [1, 5, 64, 96, 1000])
def test_sliding_window_forward_matches_reference(window):
    """window=W spans every regime: sub-block (1, 5), exactly one block
    (64), block-straddling (96), and wider-than-T (1000, == plain
    causal)."""
    q, k, v = _qkv(t=256, d=32)
    out = flash_attention(q, k, v, True, None, 64, 64, True, window)
    ref = full_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_sliding_window_wider_than_t_equals_plain_causal():
    q, k, v = _qkv(t=128, d=32)
    windowed = flash_attention(q, k, v, True, None, 64, 64, True, 1000)
    plain = flash_attention(q, k, v, True, None, 64, 64, True)
    np.testing.assert_allclose(np.asarray(windowed), np.asarray(plain))


@pytest.mark.parametrize("window", [5, 96])
def test_sliding_window_gradients_match_reference(window):
    q, k, v = _qkv(t=128, d=32)

    def loss_flash(q, k, v):
        return (
            flash_attention(q, k, v, True, None, 64, 32, True, window) ** 2
        ).sum()

    def loss_ref(q, k, v):
        return (full_attention(q, k, v, causal=True, window=window) ** 2).sum()

    gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5
        )


def test_sliding_window_shrinks_grid():
    """The windowed grids really are O(W), not O(T): step counts drop
    below the full block count, and parity holds with the shrunk grids
    active in ALL THREE passes (incl. the end-of-sequence overshoot rows
    where a derived q index past the last real block must be dead, not
    double-counted)."""
    from blendjax.ops.flash_attention import (
        _kv_window_steps,
        _q_window_steps,
    )

    # t=384, blocks 64: 6 full blocks; W=96 needs only 4 steps
    assert _kv_window_steps(6, 64, 64, 96) == 4
    assert _q_window_steps(6, 64, 64, 96) == 4
    # W wider than T: clamped to the full grid
    assert _kv_window_steps(6, 64, 64, 10_000) == 6

    q, k, v = _qkv(b=1, t=384, h=2, d=16)

    def loss_flash(q, k, v):
        return (
            flash_attention(q, k, v, True, None, 64, 64, True, 96) ** 2
        ).sum()

    def loss_ref(q, k, v):
        return (full_attention(q, k, v, causal=True, window=96) ** 2).sum()

    out = flash_attention(q, k, v, True, None, 64, 64, True, 96)
    ref = full_attention(q, k, v, causal=True, window=96)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )
    gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5
        )


def test_sliding_window_requires_causal():
    q, k, v = _qkv(t=64, d=16)
    with pytest.raises(ValueError, match="requires causal"):
        flash_attention(q, k, v, False, None, 64, 64, True, 8)
    with pytest.raises(ValueError, match="requires causal"):
        make_flash_attention(causal=False, window=8)
    with pytest.raises(ValueError, match="window requires causal"):
        full_attention(q, k, v, causal=False, window=8)


def test_make_flash_attention_window_closure():
    """The factory threads window through to the kernel (seqformer seam)."""
    q, k, v = _qkv(t=128, d=32)
    attn = make_flash_attention(causal=True, block_q=64, block_kv=64,
                                interpret=True, window=48)
    np.testing.assert_allclose(
        np.asarray(attn(q, k, v)),
        np.asarray(full_attention(q, k, v, causal=True, window=48)),
        atol=2e-5, rtol=2e-5,
    )


@pytest.mark.parametrize("h_kv", [1, 2, 4])
@pytest.mark.parametrize("causal", [False, True])
def test_gqa_forward_matches_reference(h_kv, causal):
    """Grouped-query attention (h_kv < h, incl. MQA at h_kv=1): the KV
    BlockSpec head mapping must agree with the broadcast reference."""
    q, _, _ = _qkv(t=128, d=16)
    ks = jax.random.split(jax.random.PRNGKey(9), 2)
    k = jax.random.normal(ks[0], (2, 128, h_kv, 16), jnp.float32)
    v = jax.random.normal(ks[1], (2, 128, h_kv, 16), jnp.float32)
    out = flash_attention(q, k, v, causal, None, 64, 32, True)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_gqa_gradients_match_reference_incl_window():
    """dK/dV under GQA group-sum onto the shared head (f32 partials),
    composed with sliding-window; shapes follow the kv head count."""
    q, _, _ = _qkv(t=128, d=16)
    ks = jax.random.split(jax.random.PRNGKey(10), 2)
    k = jax.random.normal(ks[0], (2, 128, 2, 16), jnp.float32)
    v = jax.random.normal(ks[1], (2, 128, 2, 16), jnp.float32)

    def loss_flash(q, k, v):
        return (
            flash_attention(q, k, v, True, None, 64, 32, True, 48) ** 2
        ).sum()

    def loss_ref(q, k, v):
        return (full_attention(q, k, v, causal=True, window=48) ** 2).sum()

    gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    assert gf[1].shape == (2, 128, 2, 16)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4
        )


def test_gqa_rejects_indivisible_heads():
    q, _, _ = _qkv(t=64, d=16)  # 4 heads
    ks = jax.random.split(jax.random.PRNGKey(11), 2)
    k = jax.random.normal(ks[0], (2, 64, 3, 16), jnp.float32)
    with pytest.raises(ValueError, match="multiple of kv heads"):
        flash_attention(q, k, k, True, None, 64, 64, True)
    with pytest.raises(ValueError, match="multiple of kv heads"):
        full_attention(q, k, k, causal=True)


def test_make_flash_attention_auto_tiles_to_sequence():
    """block='auto' sizes the tile per call via flash_block_size, so the
    closure works at lengths a fixed 128 block would reject."""
    import numpy as np

    from blendjax.ops.flash_attention import (
        flash_block_size,
        make_flash_attention,
    )
    from blendjax.parallel.ring_attention import full_attention

    assert flash_block_size(512) == 128
    assert flash_block_size(160) == 32
    assert flash_block_size(20) == 20  # falls back to the length itself

    attn = make_flash_attention(causal=True, block_q="auto",
                                block_kv="auto", interpret=True)
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 160, 2, 16),
                          jnp.float32)
    got = attn(q, q, q)
    want = full_attention(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5)

    # ragged beyond a single tile: rejected, not silently O(T^2)
    bad = jax.random.normal(jax.random.PRNGKey(1), (1, 161, 2, 16),
                            jnp.float32)
    with pytest.raises(ValueError, match="pad to a 32-multiple"):
        attn(bad, bad, bad)
