"""Golden-camera acceptance path, CI edition.

Drives the SAME producer script the real-Blender acceptance test uses
(``tests/blender/golden_camera.blend.py``) through the fake-Blender fleet
with the fake ``bpy`` installed in the child (``BLENDJAX_FAKE_BPY``), and
checks the published pixel/depth annotations against the analytic
expectations of ``golden_camera_spec`` — so the full acceptance plumbing
(launcher -> embedded script -> bpy adapter -> publisher -> wire) is
exercised on every CI run; only the ``bpy`` implementation is swapped
when a real Blender picks it up (``test_blender_integration.py``).
"""

import importlib.util
import os

import zmq

from blendjax import wire
from blendjax.btt.launcher import BlenderLauncher

HERE = os.path.dirname(os.path.abspath(__file__))
SCRIPT = os.path.join(HERE, "blender", "golden_camera.blend.py")
SPEC = os.path.join(HERE, "blender", "golden_camera_spec.py")


def _load_spec():
    mod_spec = importlib.util.spec_from_file_location("golden_camera_spec", SPEC)
    mod = importlib.util.module_from_spec(mod_spec)
    mod_spec.loader.exec_module(mod)
    return mod


def test_golden_camera_producer_matches_analytic(monkeypatch):
    spec = _load_spec()
    monkeypatch.setenv(
        "BLENDJAX_BLENDER",
        os.path.join(HERE, "helpers", "fake_blender.py"),
    )
    monkeypatch.setenv("BLENDJAX_FAKE_BPY", "1")

    with BlenderLauncher(
        scene="",
        script=SCRIPT,
        num_instances=1,
        named_sockets=["DATA"],
        start_port=14730,
        background=True,
    ) as bl:
        ctx = zmq.Context()
        try:
            sock = ctx.socket(zmq.PULL)
            sock.connect(bl.launch_info.addresses["DATA"][0])
            assert sock.poll(30000), "no golden-camera payload"
            msg = wire.recv_message(sock)
        finally:
            ctx.destroy(linger=0)

    spec.check_payload(msg)
