"""Native shm-ring transport tests: raw ring semantics (wraparound,
backpressure, EOF), the framed reader/writer pair, and the full
DataPublisher -> RemoteIterableDataset shm:// path with recording."""

import os
import threading

import numpy as np
import pytest

from blendjax.native import ring as nring

pytestmark = pytest.mark.skipif(
    not nring.native_available(), reason="native ring not built (no g++?)"
)


def _addr(tag):
    return f"shm://bjx-test-{tag}-{os.getpid()}"


def test_roundtrip_and_order():
    w = nring.ShmRingWriter(_addr("rt"), capacity_bytes=1 << 16)
    r = nring.ShmRingReader(_addr("rt"))
    try:
        for i in range(10):
            assert w.send_frames([f"msg{i}".encode(), b"x" * i])
        for i in range(10):
            frames = r.recv_frames(timeout_ms=1000)
            assert frames == [f"msg{i}".encode(), b"x" * i]
        assert r.recv_frames(timeout_ms=0) is None
    finally:
        w.close()
        r.close()


def test_wraparound_many_messages():
    # ring much smaller than total traffic -> exercises the wrap marker
    w = nring.ShmRingWriter(_addr("wrap"), capacity_bytes=1 << 14)  # 16 KiB
    r = nring.ShmRingReader(_addr("wrap"))
    payload = os.urandom(1000)
    n = 200
    errors = []

    def produce():
        for i in range(n):
            if not w.send_frames([i.to_bytes(4, "little"), payload], timeout_ms=5000):
                errors.append(i)
                return

    t = threading.Thread(target=produce)
    t.start()
    try:
        for i in range(n):
            frames = r.recv_frames(timeout_ms=5000)
            assert frames is not None, f"timeout at {i}"
            assert int.from_bytes(frames[0], "little") == i
            assert frames[1] == payload
    finally:
        t.join()
        w.close()
        r.close()
    assert not errors


def test_backpressure_blocks_writer():
    w = nring.ShmRingWriter(_addr("bp"), capacity_bytes=1 << 12)  # 4 KiB
    r = nring.ShmRingReader(_addr("bp"))
    try:
        big = b"z" * 1500
        assert w.send_frames([big], timeout_ms=200)
        assert w.send_frames([big], timeout_ms=200)
        # ring full now: bounded wait then False
        assert not w.send_frames([big], timeout_ms=200)
        # drain one -> space again
        assert r.recv_frames(timeout_ms=1000) is not None
        assert w.send_frames([big], timeout_ms=2000)
    finally:
        w.close()
        r.close()


def test_oversize_message_raises():
    w = nring.ShmRingWriter(_addr("big"), capacity_bytes=1 << 12)
    try:
        with pytest.raises(ValueError, match="larger than ring"):
            w.send_frames([b"x" * (1 << 13)])
    finally:
        w.close()


def test_eof_after_producer_close():
    w = nring.ShmRingWriter(_addr("eof"), capacity_bytes=1 << 14)
    r = nring.ShmRingReader(_addr("eof"))
    w.send_frames([b"last"])
    w.close(unlink=False)
    assert r.recv_frames(timeout_ms=1000) == [b"last"]
    with pytest.raises(EOFError):
        r.recv_frames(timeout_ms=1000)
    r.close()


def test_publisher_dataset_shm_end_to_end(tmp_path):
    from blendjax.btb.publisher import DataPublisher
    from blendjax.btt.dataset import FileDataset, RemoteIterableDataset

    addrs = [_addr("e2e-0"), _addr("e2e-1")]
    stop = threading.Event()

    def produce(addr, btid):
        pub = DataPublisher(addr, btid=btid, raw_buffers=True, sndtimeoms=200)
        i = 0
        while not stop.is_set() and i < 64:
            img = np.full((8, 8, 3), (btid * 10 + i) % 255, np.uint8)
            if pub.publish(image=img, frameid=i):
                i += 1
        pub.close()

    threads = [
        threading.Thread(target=produce, args=(a, i), daemon=True)
        for i, a in enumerate(addrs)
    ]
    for t in threads:
        t.start()
    try:
        prefix = str(tmp_path / "shmrec")
        ds = RemoteIterableDataset(addrs, max_items=16, timeoutms=10000)
        ds.enable_recording(prefix)
        items = list(ds.stream(worker_id=0, num_workers=2))  # rings split
        assert len(items) == 8
        assert all(i["btid"] == 0 for i in items)  # worker 0 owns ring 0
        assert items[0]["image"].shape == (8, 8, 3)
        # recording worked through the shm path too
        replay = FileDataset(prefix)
        assert len(replay) == 8
        np.testing.assert_array_equal(replay[0]["image"], items[0]["image"])
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)


def test_shm_timeout():
    from blendjax.btt.dataset import RemoteIterableDataset

    w = nring.ShmRingWriter(_addr("idle"), capacity_bytes=1 << 12)
    try:
        ds = RemoteIterableDataset([_addr("idle")], max_items=1, timeoutms=300)
        with pytest.raises(TimeoutError):
            list(ds)
    finally:
        w.close()


def test_launcher_shm_addresses():
    from blendjax.btt.launcher import BlenderLauncher

    bl = BlenderLauncher.__new__(BlenderLauncher)
    bl.bind_addr = "127.0.0.1"
    bl.proto = "shm"
    bl.start_port = 13000
    bl.num_instances = 2
    bl.named_sockets = ["DATA"]
    bl._nonce = "cafe0123"
    bl._shm_base = f"blendjax-{bl._nonce}"
    # the nonce makes names launch-unique so a leaked ring from a dead run
    # can never be mistaken for this launch's ring (VERDICT r2 weak #2);
    # it leads as the BASE PREFIX so one unlink_base glob sweeps every
    # object of the launch at teardown (PR-12 ShmRPC hygiene)
    assert bl._addresses()["DATA"] == [
        "shm://blendjax-cafe0123-DATA-13000",
        "shm://blendjax-cafe0123-DATA-13001",
    ]


def test_fast_stack_matches_np_stack():
    rng = np.random.default_rng(0)
    items = [rng.random((480, 640, 4)).astype(np.float32) for _ in range(8)]
    np.testing.assert_array_equal(nring.fast_stack(items), np.stack(items))
    # non-contiguous sources are handled via a contiguous copy
    views = [a[:, ::2, :] for a in items]
    np.testing.assert_array_equal(nring.fast_stack(views), np.stack(views))
    # uint8 + preallocated out buffer
    bytes_items = [rng.integers(0, 255, (64, 64, 3), dtype=np.uint8) for _ in range(4)]
    out = np.empty((4, 64, 64, 3), np.uint8)
    res = nring.fast_stack(bytes_items, out=out)
    assert res is out
    np.testing.assert_array_equal(out, np.stack(bytes_items))


def test_fast_stack_rejects_mismatch():
    with pytest.raises(ValueError):
        nring.fast_stack([np.zeros((2, 2)), np.zeros((2, 3))])
    with pytest.raises(ValueError):
        nring.fast_stack([np.zeros((2, 2), np.float32), np.zeros((2, 2), np.float64)])


def test_fast_stack_validates_out():
    items = [np.zeros((64, 64), np.float32) for _ in range(4)]
    with pytest.raises(ValueError):
        nring.fast_stack(items, out=np.empty((2, 64, 64), np.float32))
    with pytest.raises(ValueError):
        nring.fast_stack(items, out=np.empty((4, 64, 64), np.float64))
    with pytest.raises(ValueError):
        nring.fast_stack(items, out=np.empty((4, 64, 128), np.float32)[:, :, ::2])


def test_recv_frames_large_payload_buffer_semantics():
    """Frames >= 64KiB come back as uint8 ndarrays (GIL-released copy-out);
    they must decode identically to the bytes path."""
    from blendjax import wire

    addr = _addr("bigframe")
    w = nring.ShmRingWriter(addr, capacity_bytes=8 << 20)
    r = nring.ShmRingReader(addr)
    try:
        img = np.arange(512 * 512, dtype=np.uint8).reshape(512, 512)  # 256KB
        frames_out = wire.encode({"image": img, "frameid": 3}, raw_buffers=True)
        assert w.send_frames(frames_out, timeout_ms=1000)
        frames_in = r.recv_frames(timeout_ms=1000)
        assert isinstance(frames_in[1], np.ndarray)  # large payload
        msg = wire.decode(frames_in)
        np.testing.assert_array_equal(msg["image"], img)
        assert msg["frameid"] == 3
    finally:
        r.close()
        w.close(unlink=True)


def _produce_n(addr, btid, n, shape=(32, 32, 3), big_from=None):
    """Publish n frames; from index big_from on, switch image shape
    (schema-drift injection).  Bounded by a deadline so a consumer that
    reads fewer than n items (ring full -> publish timeouts) doesn't leave
    this thread spinning until interpreter exit."""
    import time

    from blendjax.btb.publisher import DataPublisher

    pub = DataPublisher(addr, btid=btid, raw_buffers=True, sndtimeoms=500)
    deadline = time.monotonic() + 30.0
    stalls = 0
    i = 0
    while i < n and stalls < 6 and time.monotonic() < deadline:
        shp = shape if big_from is None or i < big_from else (shape[0] * 2,) + shape[1:]
        img = np.full(shp, (btid * 10 + i) % 255, np.uint8)
        if pub.publish(image=img, frameid=i, tag=f"f{i}"):
            i += 1
            stalls = 0
        else:
            stalls += 1
    pub.close()


def test_stream_batches_matches_item_path():
    """Zero-copy batch assembly must produce byte-identical batches to the
    per-item stream + collate path."""
    from blendjax.btt.collate import collate
    from blendjax.btt.dataset import RemoteIterableDataset

    shape = (64, 64, 4)  # 16KB/frame -> small-copy path; still exercises zc
    addr_a, addr_b = _addr("zc-a"), _addr("zc-b")
    ta = threading.Thread(target=_produce_n, args=(addr_a, 0, 16, shape), daemon=True)
    tb = threading.Thread(target=_produce_n, args=(addr_b, 1, 16, shape), daemon=True)
    ta.start()
    ds = RemoteIterableDataset([addr_a], max_items=12, timeoutms=10000)
    assert ds.supports_batched_stream()
    batches = list(ds.stream_batches(4))
    ta.join(timeout=10)
    assert len(batches) == 3
    for b in batches:
        assert b["image"].shape == (4,) + shape
        assert b["image"].dtype == np.uint8
        assert b["btid"].tolist() == [0] * 4
        assert len(b["tag"]) == 4 and isinstance(b["tag"][0], str)
    # parity against the generic path on an identical stream
    tb.start()
    ds2 = RemoteIterableDataset([addr_b], max_items=12, timeoutms=10000)
    items2 = list(ds2.stream())
    ref = [collate(items2[i : i + 4]) for i in range(0, 12, 4)]
    tb.join(timeout=10)
    for b, r in zip(batches, ref):
        # same frames modulo btid (different producer ids)
        np.testing.assert_array_equal(
            b["image"][:, :, :, 0] - b["btid"][0] * 10 % 255,
            r["image"][:, :, :, 0] - r["btid"][0] * 10 % 255,
        )
        np.testing.assert_array_equal(b["frameid"], r["frameid"])


def test_stream_batches_partial_and_drop_last():
    from blendjax.btt.dataset import RemoteIterableDataset

    addr = _addr("zc-partial")
    t = threading.Thread(target=_produce_n, args=(addr, 0, 10), daemon=True)
    t.start()
    ds = RemoteIterableDataset([addr], max_items=10, timeoutms=10000)
    batches = list(ds.stream_batches(4, drop_last=False))
    t.join(timeout=10)
    assert [b["image"].shape[0] for b in batches] == [4, 4, 2]
    assert batches[-1]["frameid"].tolist() == [8, 9]


def test_stream_batches_schema_drift_degrades():
    """A key whose shape changes mid-batch degrades to the ragged-list
    collate rules instead of failing the stream."""
    from blendjax.btt.dataset import RemoteIterableDataset

    addr = _addr("zc-drift")
    t = threading.Thread(
        target=_produce_n, args=(addr, 0, 8), kwargs={"big_from": 2}, daemon=True
    )
    t.start()
    ds = RemoteIterableDataset([addr], max_items=8, timeoutms=10000)
    batches = list(ds.stream_batches(4))
    t.join(timeout=10)
    assert len(batches) == 2
    first = batches[0]
    assert isinstance(first["image"], list)  # ragged -> list of arrays
    assert first["image"][0].shape == (32, 32, 3)
    assert first["image"][2].shape == (64, 32, 3)
    # second batch is uniform again (all big frames) -> stacked
    assert batches[1]["image"].shape == (4, 64, 32, 3)


def test_loader_uses_batched_stream_on_shm(tmp_path):
    from blendjax.btt.dataset import RemoteIterableDataset
    from blendjax.btt.loader import BatchLoader

    addr = _addr("zc-loader")
    t = threading.Thread(target=_produce_n, args=(addr, 3, 16), daemon=True)
    t.start()
    ds = RemoteIterableDataset([addr], max_items=16, timeoutms=10000)
    with BatchLoader(ds, batch_size=8, num_workers=1) as loader:
        batches = list(loader)
    t.join(timeout=10)
    assert len(batches) == 2
    assert batches[0]["image"].shape == (8, 32, 32, 3)
    assert batches[0]["btid"].tolist() == [3] * 8


def test_stream_batches_nested_container_arrays():
    """Arrays nested inside list values must decode (not leak raw
    placeholders) and stack exactly like the generic collate path."""
    from blendjax.btb.publisher import DataPublisher
    from blendjax.btt.dataset import RemoteIterableDataset

    addr = _addr("zc-nested")

    def produce():
        pub = DataPublisher(addr, btid=0, raw_buffers=True, sndtimeoms=500)
        i = 0
        while i < 8:
            pts = [np.full((3, 2), i, np.float32), np.full((3, 2), i + 1, np.float32)]
            if pub.publish(points=pts, frameid=i):
                i += 1
        pub.close()

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    ds = RemoteIterableDataset([addr], max_items=8, timeoutms=10000)
    batches = list(ds.stream_batches(4))
    t.join(timeout=10)
    assert len(batches) == 2
    pts = batches[0]["points"]
    # list of 2 positions, each stacked over the batch -> (4, 3, 2)
    assert isinstance(pts, list) and len(pts) == 2
    assert pts[0].shape == (4, 3, 2) and pts[0].dtype == np.float32
    np.testing.assert_array_equal(pts[0][:, 0, 0], [0, 1, 2, 3])
    np.testing.assert_array_equal(pts[1][:, 0, 0], [1, 2, 3, 4])


def test_stream_batches_key_semantics_match_generic_collate():
    """Missing first-message key -> KeyError; extra later key -> dropped."""
    from blendjax.btb.publisher import DataPublisher
    from blendjax.btt.dataset import RemoteIterableDataset

    addr = _addr("zc-keys")

    def produce(msgs):
        pub = DataPublisher(addr, btid=0, raw_buffers=True, sndtimeoms=500)
        i = 0
        while i < len(msgs):
            if pub.publish(**msgs[i]):
                i += 1
        pub.close()

    img = np.zeros((4, 4), np.uint8)
    # message 2 grows an extra key (dropped); message 3 is complete again
    msgs = [
        {"image": img, "frameid": 0},
        {"image": img, "frameid": 1},
        {"image": img, "frameid": 2, "extra": 7},
        {"image": img, "frameid": 3},
    ]
    t = threading.Thread(target=produce, args=(msgs,), daemon=True)
    t.start()
    ds = RemoteIterableDataset([addr], max_items=4, timeoutms=10000)
    (batch,) = list(ds.stream_batches(4))
    t.join(timeout=10)
    assert "extra" not in batch
    assert batch["frameid"].tolist() == [0, 1, 2, 3]

    # missing key fails loudly instead of silently misaligning slots
    addr2 = _addr("zc-keys2")

    def produce2():
        pub = DataPublisher(addr2, btid=0, raw_buffers=True, sndtimeoms=500)
        ms = [{"image": img, "frameid": 0}, {"image": img}]
        i = 0
        while i < len(ms):
            if pub.publish(**ms[i]):
                i += 1
        pub.close()

    t2 = threading.Thread(target=produce2, daemon=True)
    t2.start()
    ds2 = RemoteIterableDataset([addr2], max_items=2, timeoutms=10000)
    with pytest.raises(KeyError):
        list(ds2.stream_batches(2))
    t2.join(timeout=10)


def test_item_override_disables_batched_stream():
    """A subclass overriding _item() (the documented override point) must
    NOT be routed through the zero-copy batched path, which would silently
    skip its per-item transform; it falls back to stream() + collate and
    the transform is applied."""
    from blendjax.btt.dataset import RemoteIterableDataset
    from blendjax.btt.loader import BatchLoader

    class Doubling(RemoteIterableDataset):
        def _item(self, item):
            item["frameid"] = item["frameid"] * 2
            return item

    addr = _addr("zc-override")
    t = threading.Thread(target=_produce_n, args=(addr, 0, 8), daemon=True)
    t.start()
    ds = Doubling([addr], max_items=8, timeoutms=10000)
    assert not ds.supports_batched_stream()
    with BatchLoader(ds, batch_size=4, num_workers=1) as loader:
        batches = list(loader)
    t.join(timeout=10)
    assert len(batches) == 2
    got = sorted(
        int(v) for b in batches for v in np.asarray(b["frameid"]).ravel()
    )
    assert got == [0, 2, 4, 6, 8, 10, 12, 14]


def test_reader_survives_producer_respawn():
    """Generation change: a respawned producer's bjr_create unlinks and
    recreates the ring; the reader must drain the old generation's buffered
    records, detect the identity change, and remap the new ring
    (VERDICT r01 weak #6)."""
    addr = _addr("gen")
    w_a = nring.ShmRingWriter(addr, capacity_bytes=1 << 16)
    r = nring.ShmRingReader(addr)
    assert w_a.send_frames([b"a0"]) and w_a.send_frames([b"a1"])
    assert r.recv_frames(1000) == [b"a0"]
    # producer "crashes" (never calls close -> producer_closed stays 0)
    # and is respawned under the same address
    w_b = nring.ShmRingWriter(addr, capacity_bytes=1 << 16)
    assert w_b.send_frames([b"b0"])
    # old generation drains first; then the reader reopens transparently
    assert r.recv_frames(5000) == [b"a1"]
    assert r.recv_frames(5000) == [b"b0"]
    assert r.reconnects == 1
    r.close()
    w_b.close(unlink=True)
    w_a.close(unlink=False)  # stale mapping cleanup, nothing to unlink


def test_reader_raises_when_ring_gone_for_good():
    """Producer crashed and nothing respawned it: the reader must fail
    with a distinguishable error within the timeout, not hang."""
    addr = _addr("gone")
    w = nring.ShmRingWriter(addr, capacity_bytes=1 << 14)
    r = nring.ShmRingReader(addr)
    nring.unlink_address(addr)
    with pytest.raises(ConnectionResetError, match="vanished"):
        r.recv_frames(1200)
    r.close()
    w.close(unlink=False)


def test_reader_auto_reopen_disabled():
    addr = _addr("noreopen")
    w_a = nring.ShmRingWriter(addr, capacity_bytes=1 << 14)
    r = nring.ShmRingReader(addr, auto_reopen=False)
    w_b = nring.ShmRingWriter(addr, capacity_bytes=1 << 14)
    with pytest.raises(ConnectionResetError):
        r.recv_frames(1200)
    r.close()
    w_b.close(unlink=True)
    w_a.close(unlink=False)
