"""Native shm-ring transport tests: raw ring semantics (wraparound,
backpressure, EOF), the framed reader/writer pair, and the full
DataPublisher -> RemoteIterableDataset shm:// path with recording."""

import os
import threading

import numpy as np
import pytest

from blendjax.native import ring as nring

pytestmark = pytest.mark.skipif(
    not nring.native_available(), reason="native ring not built (no g++?)"
)


def _addr(tag):
    return f"shm://bjx-test-{tag}-{os.getpid()}"


def test_roundtrip_and_order():
    w = nring.ShmRingWriter(_addr("rt"), capacity_bytes=1 << 16)
    r = nring.ShmRingReader(_addr("rt"))
    try:
        for i in range(10):
            assert w.send_frames([f"msg{i}".encode(), b"x" * i])
        for i in range(10):
            frames = r.recv_frames(timeout_ms=1000)
            assert frames == [f"msg{i}".encode(), b"x" * i]
        assert r.recv_frames(timeout_ms=0) is None
    finally:
        w.close()
        r.close()


def test_wraparound_many_messages():
    # ring much smaller than total traffic -> exercises the wrap marker
    w = nring.ShmRingWriter(_addr("wrap"), capacity_bytes=1 << 14)  # 16 KiB
    r = nring.ShmRingReader(_addr("wrap"))
    payload = os.urandom(1000)
    n = 200
    errors = []

    def produce():
        for i in range(n):
            if not w.send_frames([i.to_bytes(4, "little"), payload], timeout_ms=5000):
                errors.append(i)
                return

    t = threading.Thread(target=produce)
    t.start()
    try:
        for i in range(n):
            frames = r.recv_frames(timeout_ms=5000)
            assert frames is not None, f"timeout at {i}"
            assert int.from_bytes(frames[0], "little") == i
            assert frames[1] == payload
    finally:
        t.join()
        w.close()
        r.close()
    assert not errors


def test_backpressure_blocks_writer():
    w = nring.ShmRingWriter(_addr("bp"), capacity_bytes=1 << 12)  # 4 KiB
    r = nring.ShmRingReader(_addr("bp"))
    try:
        big = b"z" * 1500
        assert w.send_frames([big], timeout_ms=200)
        assert w.send_frames([big], timeout_ms=200)
        # ring full now: bounded wait then False
        assert not w.send_frames([big], timeout_ms=200)
        # drain one -> space again
        assert r.recv_frames(timeout_ms=1000) is not None
        assert w.send_frames([big], timeout_ms=2000)
    finally:
        w.close()
        r.close()


def test_oversize_message_raises():
    w = nring.ShmRingWriter(_addr("big"), capacity_bytes=1 << 12)
    try:
        with pytest.raises(ValueError, match="larger than ring"):
            w.send_frames([b"x" * (1 << 13)])
    finally:
        w.close()


def test_eof_after_producer_close():
    w = nring.ShmRingWriter(_addr("eof"), capacity_bytes=1 << 14)
    r = nring.ShmRingReader(_addr("eof"))
    w.send_frames([b"last"])
    w.close(unlink=False)
    assert r.recv_frames(timeout_ms=1000) == [b"last"]
    with pytest.raises(EOFError):
        r.recv_frames(timeout_ms=1000)
    r.close()


def test_publisher_dataset_shm_end_to_end(tmp_path):
    from blendjax.btb.publisher import DataPublisher
    from blendjax.btt.dataset import FileDataset, RemoteIterableDataset

    addrs = [_addr("e2e-0"), _addr("e2e-1")]
    stop = threading.Event()

    def produce(addr, btid):
        pub = DataPublisher(addr, btid=btid, raw_buffers=True, sndtimeoms=200)
        i = 0
        while not stop.is_set() and i < 64:
            img = np.full((8, 8, 3), (btid * 10 + i) % 255, np.uint8)
            if pub.publish(image=img, frameid=i):
                i += 1
        pub.close()

    threads = [
        threading.Thread(target=produce, args=(a, i), daemon=True)
        for i, a in enumerate(addrs)
    ]
    for t in threads:
        t.start()
    try:
        prefix = str(tmp_path / "shmrec")
        ds = RemoteIterableDataset(addrs, max_items=16, timeoutms=10000)
        ds.enable_recording(prefix)
        items = list(ds.stream(worker_id=0, num_workers=2))  # rings split
        assert len(items) == 8
        assert all(i["btid"] == 0 for i in items)  # worker 0 owns ring 0
        assert items[0]["image"].shape == (8, 8, 3)
        # recording worked through the shm path too
        replay = FileDataset(prefix)
        assert len(replay) == 8
        np.testing.assert_array_equal(replay[0]["image"], items[0]["image"])
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)


def test_shm_timeout():
    from blendjax.btt.dataset import RemoteIterableDataset

    w = nring.ShmRingWriter(_addr("idle"), capacity_bytes=1 << 12)
    try:
        ds = RemoteIterableDataset([_addr("idle")], max_items=1, timeoutms=300)
        with pytest.raises(TimeoutError):
            list(ds)
    finally:
        w.close()


def test_launcher_shm_addresses():
    from blendjax.btt.launcher import BlenderLauncher

    bl = BlenderLauncher.__new__(BlenderLauncher)
    bl.bind_addr = "127.0.0.1"
    bl.proto = "shm"
    bl.start_port = 13000
    bl.num_instances = 2
    bl.named_sockets = ["DATA"]
    assert bl._addresses()["DATA"] == [
        "shm://blendjax-DATA-13000",
        "shm://blendjax-DATA-13001",
    ]


def test_fast_stack_matches_np_stack():
    rng = np.random.default_rng(0)
    items = [rng.random((480, 640, 4)).astype(np.float32) for _ in range(8)]
    np.testing.assert_array_equal(nring.fast_stack(items), np.stack(items))
    # non-contiguous sources are handled via a contiguous copy
    views = [a[:, ::2, :] for a in items]
    np.testing.assert_array_equal(nring.fast_stack(views), np.stack(views))
    # uint8 + preallocated out buffer
    bytes_items = [rng.integers(0, 255, (64, 64, 3), dtype=np.uint8) for _ in range(4)]
    out = np.empty((4, 64, 64, 3), np.uint8)
    res = nring.fast_stack(bytes_items, out=out)
    assert res is out
    np.testing.assert_array_equal(out, np.stack(bytes_items))


def test_fast_stack_rejects_mismatch():
    with pytest.raises(ValueError):
        nring.fast_stack([np.zeros((2, 2)), np.zeros((2, 3))])
    with pytest.raises(ValueError):
        nring.fast_stack([np.zeros((2, 2), np.float32), np.zeros((2, 2), np.float64)])


def test_fast_stack_validates_out():
    items = [np.zeros((64, 64), np.float32) for _ in range(4)]
    with pytest.raises(ValueError):
        nring.fast_stack(items, out=np.empty((2, 64, 64), np.float32))
    with pytest.raises(ValueError):
        nring.fast_stack(items, out=np.empty((4, 64, 64), np.float64))
    with pytest.raises(ValueError):
        nring.fast_stack(items, out=np.empty((4, 64, 128), np.float32)[:, :, ::2])


def test_recv_frames_large_payload_buffer_semantics():
    """Frames >= 64KiB come back as uint8 ndarrays (GIL-released copy-out);
    they must decode identically to the bytes path."""
    from blendjax import wire

    addr = _addr("bigframe")
    w = nring.ShmRingWriter(addr, capacity_bytes=8 << 20)
    r = nring.ShmRingReader(addr)
    try:
        img = np.arange(512 * 512, dtype=np.uint8).reshape(512, 512)  # 256KB
        frames_out = wire.encode({"image": img, "frameid": 3}, raw_buffers=True)
        assert w.send_frames(frames_out, timeout_ms=1000)
        frames_in = r.recv_frames(timeout_ms=1000)
        assert isinstance(frames_in[1], np.ndarray)  # large payload
        msg = wire.decode(frames_in)
        np.testing.assert_array_equal(msg["image"], img)
        assert msg["frameid"] == 3
    finally:
        r.close()
        w.close(unlink=True)
