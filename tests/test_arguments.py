"""Arg-protocol tests (reference behavior: ``tests/test_launcher.py:20-44``
validates btid/btseed/btsockets/remainder wiring)."""

import pytest

from blendjax.btb.arguments import parse_blendtorch_args


def test_parse_full():
    argv = [
        "blender", "--background", "--python", "s.py", "--",
        "-btid", "2", "-btseed", "12", "-btsockets",
        "DATA=tcp://127.0.0.1:11000", "CTRL=tcp://127.0.0.1:11001",
        "--render-every", "3",
    ]
    args, remainder = parse_blendtorch_args(argv)
    assert args.btid == 2
    assert args.btseed == 12
    assert args.btsockets == {
        "DATA": "tcp://127.0.0.1:11000",
        "CTRL": "tcp://127.0.0.1:11001",
    }
    assert remainder == ["--render-every", "3"]


def test_parse_no_separator_uses_all():
    args, rem = parse_blendtorch_args(["-btid", "5"])
    assert args.btid == 5 and rem == []


def test_parse_defaults():
    args, rem = parse_blendtorch_args(["--"])
    assert args.btid == 0 and args.btseed == 0 and args.btsockets == {}


def test_bad_socket_entry():
    with pytest.raises(ValueError):
        parse_blendtorch_args(["--", "-btsockets", "DATAtcp://x"])
