"""Race-detection / crash-churn stress harness for the native shm
transport (SURVEY.md §5 "race detection / sanitizers" — the reference has
none; blendjax's real concurrency lives exactly here: loader worker
threads rotating multiple SPSC rings while producer processes are
SIGKILLed and respawned under the same names).

Two layers:

1. ``test_churn_kill_respawn`` (always on): 3 producer processes, a
   2-worker ``BatchLoader`` fan-in, and a killer loop that SIGKILLs a
   producer (round-robin) every ~1.2 s and respawns it at the SAME address with
   a bumped generation counter.  Asserts the stream never stalls past its
   timeout, per-(btid, gen) frameids stay strictly increasing (no
   duplicated/reordered delivery within a generation), and **no
   stale-generation frame arrives after a newer generation was seen** for
   that producer — the data-poisoning class the round-2 judge caught
   live.
2. ``test_tsan_stress_binary`` (runs when a toolchain is present;
   skipped otherwise): ``blendjax/native/tsan_stress.cpp`` — writer,
   reader, and generation-churn threads over the real ring code compiled
   ``-fsanitize=thread`` in ONE process, so TSAN instruments both sides
   of every happens-before edge without dragging CPython under the
   sanitizer (LD_PRELOADing TSAN into the interpreter is a 30x slowdown
   and a false-positive farm).  ``make -C blendjax/native tsan-stress``
   runs it standalone.
"""

import os

import signal
import subprocess
import sys
import threading
import time

import pytest

from blendjax.native import native_available

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PRODUCER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "helpers", "churn_producer.py")

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native ring not built"
)


def _spawn(addr, btid, gen, env):
    # no preexec_fn: fork hooks deadlock under active threads (the killer
    # + loader workers run while spawning); the producer sets its own
    # PR_SET_PDEATHSIG at startup instead
    return subprocess.Popen(
        [sys.executable, PRODUCER, "--addr", addr, "--btid", str(btid),
         "--gen", str(gen), "--rate-hz", "800"],
        env=env,
        stderr=subprocess.PIPE,
        text=True,
    )


def _ring_ino(addr):
    name = addr[len("shm://"):]
    try:
        return os.stat(os.path.join("/dev/shm", name)).st_ino
    except OSError:
        return None


def _run_churn(env, max_seconds=45.0, n_producers=3):
    """Shared harness body; returns (n_messages, child_stderrs).

    The killer paces itself on the RESPAWN, not a fixed interval: after
    SIGKILLing a producer it waits until the replacement has actually
    recreated the ring (inode change) before moving to the next target.
    A fixed interval shorter than producer startup (~2.5 s of python
    imports on a loaded 1-core host) would kill every replacement before
    it ever creates its ring — then no post-respawn frame can exist and
    the test starves on harness timing, not product behavior.

    The consume loop runs until every producer's post-respawn generation
    has been DELIVERED (or ``max_seconds``), so the pass criterion is the
    heal itself, not a wall-clock guess.
    """
    from blendjax.btt.dataset import RemoteIterableDataset
    from blendjax.btt.loader import BatchLoader

    addrs = [
        f"shm://bjx-test-churn-{os.getpid()}-{i}" for i in range(n_producers)
    ]
    gens = [0] * n_producers
    procs = [_spawn(addrs[i], i, 0, env) for i in range(n_producers)]
    dead_err = []

    stop = threading.Event()

    def killer():
        k = 0
        while not stop.is_set():
            i = k % n_producers  # round-robin: every producer gets cycled
            k += 1
            p = procs[i]
            old_ino = _ring_ino(addrs[i])
            try:
                os.kill(p.pid, signal.SIGKILL)
            except OSError:
                pass
            _, err = p.communicate()
            if err:
                dead_err.append(err)
            gens[i] += 1
            procs[i] = _spawn(addrs[i], i, gens[i], env)
            # pace on the respawn: next kill only after this replacement
            # recreated its ring
            deadline = time.monotonic() + 20
            while (
                not stop.is_set()
                and time.monotonic() < deadline
                and _ring_ino(addrs[i]) == old_ino
            ):
                time.sleep(0.05)
            stop.wait(0.3)

    kt = threading.Thread(target=killer, daemon=True)

    def healed():
        return all(
            last_frame.get(b, (0,))[0] >= 1 for b in range(n_producers)
        )

    last_frame = {}  # btid -> (gen, frameid) high-water mark
    n = 0
    ds = RemoteIterableDataset(addrs, max_items=10**9, timeoutms=60000)
    loader = BatchLoader(ds, batch_size=8, num_workers=2)
    try:
        # all producers must have created their rings before the first
        # consume: on a contended 1-core host (full-suite run) the three
        # child interpreters can take tens of seconds to start
        deadline = time.monotonic() + 90
        while (
            any(_ring_ino(a) is None for a in addrs)
            and time.monotonic() < deadline
        ):
            time.sleep(0.1)
        it = iter(loader)
        next(it)  # all rings up before the killing starts
        kt.start()
        t0 = time.monotonic()
        while time.monotonic() - t0 < max_seconds and not healed():
            batch = next(it)  # a stall past timeoutms raises -> test fails
            for btid, gen, frameid in zip(
                batch["btid"], batch["gen"], batch["frameid"]
            ):
                btid, gen, frameid = int(btid), int(gen), int(frameid)
                prev = last_frame.get(btid)
                if prev is not None:
                    pgen, pframe = prev
                    assert gen >= pgen, (
                        f"stale generation delivered: btid {btid} gen {gen} "
                        f"after gen {pgen} (poisoned-ring class bug)"
                    )
                    if gen == pgen:
                        assert frameid > pframe, (
                            f"non-monotonic frameid within btid {btid} "
                            f"gen {gen}: {frameid} after {pframe}"
                        )
                last_frame[btid] = (gen, frameid)
                n += 1
    finally:
        stop.set()
        if kt.ident is not None:  # joining an unstarted thread raises a
            kt.join(timeout=5)    # RuntimeError that masks the real failure
        loader.close()
        for p in procs:
            p.kill()
        for p in procs:
            try:
                _, err = p.communicate(timeout=5)
                if err:
                    dead_err.append(err)
            except subprocess.TimeoutExpired:
                pass
        from blendjax.native import unlink_address

        for a in addrs:
            unlink_address(a)
    assert n > 100, f"churn harness consumed only {n} messages"
    assert all(g >= 1 for g in gens), "killer never cycled some producer"
    # the heal path must have actually RUN: every producer's post-respawn
    # frames were delivered (a silently-broken reopen would otherwise pass
    # on the surviving producers' traffic alone)
    for btid in range(n_producers):
        assert btid in last_frame, f"producer {btid} never delivered"
        assert last_frame[btid][0] >= 1, (
            f"producer {btid}: no post-respawn generation was ever "
            f"delivered (reader failed to heal onto the recreated ring)"
        )
    return n, dead_err


def _base_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return env


def test_churn_kill_respawn():
    _run_churn(_base_env())


def test_tsan_stress_binary():
    """ringbuf.cpp under ThreadSanitizer: writer + reader + generation
    churn in one process (both sides of every happens-before edge
    instrumented, no CPython noise).  Builds on demand; skips without a
    toolchain."""
    native_dir = os.path.join(REPO, "blendjax", "native")
    try:
        r = subprocess.run(
            ["make", "-s", "tsan_stress"], cwd=native_dir,
            capture_output=True, text=True,
        )
    except FileNotFoundError:
        pytest.skip("make not available")
    if r.returncode != 0:
        pytest.skip(f"TSAN build unavailable: {r.stderr[-300:]}")
    r = subprocess.run(
        [os.path.join(native_dir, "tsan_stress")],
        capture_output=True, text=True, timeout=180,
    )
    assert r.returncode == 0, f"tsan_stress failed:\n{r.stderr[-4000:]}"
    assert "WARNING: ThreadSanitizer" not in r.stderr, (
        f"data race in ring library:\n{r.stderr[-4000:]}"
    )
