"""SeqFormer: shapes, learning, and 4-way-parallel step parity.

The load-bearing tests are the parity ones: the dp x sp x tp (x ep)
sharded training step on the 8-device mesh must produce the same loss and
parameters as the plain single-device step — sharding is a layout choice,
not a numerics choice.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from blendjax.models import seqformer
from blendjax.models.train import TrainState, make_train_step
from blendjax.parallel import make_mesh, make_seqformer_train_step

OBS, B, T = 6, 4, 16


def _batch(key):
    seq = jax.random.normal(key, (B, T + 1, OBS), jnp.float32)
    return seqformer.make_episode_batch(seq)


def _params(n_experts=0):
    return seqformer.init(
        jax.random.PRNGKey(0),
        obs_dim=OBS,
        d_model=32,
        n_heads=4,
        n_layers=2,
        n_experts=n_experts,
        max_len=64,
    )


def test_forward_shape():
    params = _params()
    batch = _batch(jax.random.PRNGKey(1))
    out = seqformer.apply(params, batch["obs"])
    assert out.shape == (B, T, OBS)
    assert np.isfinite(np.asarray(out)).all()


def test_causality():
    """Changing the future must not change past predictions."""
    params = _params()
    batch = _batch(jax.random.PRNGKey(1))
    out = seqformer.apply(params, batch["obs"], compute_dtype=jnp.float32)
    poked = batch["obs"].at[:, T // 2 :].add(100.0)
    out2 = seqformer.apply(params, poked, compute_dtype=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(out[:, : T // 2]), np.asarray(out2[:, : T // 2]), atol=1e-5
    )
    assert not np.allclose(np.asarray(out[:, T // 2 :]), np.asarray(out2[:, T // 2 :]))


@pytest.mark.parametrize("n_experts", [0, 4])
def test_loss_decreases(n_experts):
    params = _params(n_experts)
    batch = _batch(jax.random.PRNGKey(1))
    state = TrainState.create(params, optax.adam(1e-2))
    step = make_train_step(
        lambda p, b: seqformer.loss_fn(p, b, compute_dtype=jnp.float32),
        optax.adam(1e-2),
    )
    losses = []
    for _ in range(10):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9


@pytest.mark.parametrize(
    "n_experts,attn_impl",
    [(0, "ring"), (0, "ulysses"), (4, "ring"), (4, "ring_flash"),
     (0, "zigzag_flash")],
)
def test_sharded_step_matches_single_device(n_experts, attn_impl):
    mesh = make_mesh({"data": 2, "seq": 2, "model": 2})
    params = _params(n_experts)
    batch = _batch(jax.random.PRNGKey(1))

    # reference: plain step, float32 compute, no sharding.  SGD so the
    # update is linear in the gradient (adam's rescaled first step would
    # amplify float-accumulation noise into sign flips).
    opt = optax.sgd(0.1)
    ref_step = make_train_step(
        lambda p, b: seqformer.loss_fn(p, b, compute_dtype=jnp.float32),
        opt,
        donate=False,
    )
    ref_state, ref_loss = ref_step(TrainState.create(params, opt), batch)

    # sharded: force float32 compute for exact comparison
    import functools

    from blendjax.parallel import make_ring_attention, seqformer_rules
    from blendjax.parallel.sharding import make_sharded_train_step
    from jax.sharding import NamedSharding, PartitionSpec as P

    attn = make_ring_attention(
        mesh,
        causal=True,
        impl=attn_impl,
        batch_axis="data",
        head_axis="model" if attn_impl == "ring" else None,
    )
    init_sharded, step = make_sharded_train_step(
        functools.partial(
            seqformer.loss_fn, attn_fn=attn, compute_dtype=jnp.float32
        ),
        opt,
        mesh,
        rules=seqformer_rules("model"),
    )
    state = init_sharded(params)
    sharded_batch = jax.device_put(
        batch, NamedSharding(mesh, P("data", "seq", None))
    )
    state, loss = step(state, sharded_batch)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        state.params,
        ref_state.params,
    )


def test_builder_end_to_end():
    """The packaged builder (bf16, adam, ring) trains to a lower loss."""
    mesh = make_mesh({"data": 2, "seq": 2, "model": 2})
    init_sharded, step, batch_sharding = make_seqformer_train_step(
        optax.adam(1e-2), mesh
    )
    state = init_sharded(_params(n_experts=4))
    batch = jax.device_put(_batch(jax.random.PRNGKey(1)), batch_sharding)
    losses = []
    for _ in range(8):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_train_step_ulysses_flash_parity():
    """attn_impl='ulysses_flash' (Ulysses all-to-all with the Pallas
    flash kernel as the per-head-group inner attention) produces the
    same loss as plain ulysses on the data x seq mesh."""
    import optax

    from blendjax.parallel import make_mesh
    from blendjax.parallel.sharding import make_seqformer_train_step

    mesh = make_mesh({"data": 2, "seq": 2, "model": 1})
    params = seqformer.init(
        jax.random.PRNGKey(0), obs_dim=4, d_model=16, n_heads=2,
        n_layers=1, max_len=64,
    )
    rng = np.random.default_rng(0)
    episodes = rng.standard_normal((4, 65, 4)).astype(np.float32)
    batch = seqformer.make_episode_batch(episodes)

    losses = {}
    for impl in ("ulysses", "ulysses_flash"):
        init_sharded, step, sharding = make_seqformer_train_step(
            optax.adam(1e-3), mesh, attn_impl=impl
        )
        # fresh param buffers: the donated train step deletes its input
        # state, and init_sharded may alias already-placed arrays
        state = init_sharded(jax.tree.map(jnp.array, params))
        state, loss = step(state, jax.device_put(batch, sharding))
        losses[impl] = float(loss)
    # bf16-level agreement: the default inner attention computes in the
    # model's bf16 compute dtype while the flash kernel is f32 inside
    assert losses["ulysses"] == pytest.approx(
        losses["ulysses_flash"], rel=5e-3
    )


def test_episode_loss_matches_obs_target_split():
    """episode_loss_fn (device-side slicing, half the wire bytes) is
    numerically identical to loss_fn over make_episode_batch's host-side
    views."""
    import numpy as np

    from blendjax.models import seqformer

    params = seqformer.init(
        jax.random.PRNGKey(0), obs_dim=5, d_model=32, n_heads=4,
        n_layers=2, max_len=12,
    )
    seq = jax.random.normal(jax.random.PRNGKey(1), (3, 13, 5), jnp.float32)
    ref = seqformer.loss_fn(params, seqformer.make_episode_batch(seq))
    ep = seqformer.episode_loss_fn(params, {"episode": seq})
    np.testing.assert_allclose(float(ep), float(ref), rtol=1e-6)

    # the benchmark's float16 wire dtype: not bit-identical (quantized
    # targets, disclosed in the artifact) but must stay numerically close
    ep16 = seqformer.episode_loss_fn(
        params, {"episode": seq.astype(jnp.float16)}
    )
    np.testing.assert_allclose(float(ep16), float(ref), rtol=5e-3)


def test_moe_stats_rejects_expertless_params():
    """ADVICE r4: params with n_experts=0 have no routing to measure —
    moe_stats must raise a descriptive error, not ZeroDivisionError."""
    import pytest

    from blendjax.models import seqformer

    params = seqformer.init(
        jax.random.PRNGKey(0), obs_dim=5, d_model=32, n_heads=4,
        n_layers=2, max_len=12,
    )
    batch = seqformer.make_episode_batch(
        jax.random.normal(jax.random.PRNGKey(1), (2, 13, 5), jnp.float32)
    )
    with pytest.raises(ValueError, match="n_experts"):
        seqformer.moe_stats(params, batch)


def test_train_step_windowed_ring_parity():
    """Sliding-window sequence parallelism through the full sharded
    train step: windowed ring (and ring_flash) losses + gradients match
    a single-device step using the windowed reference attention, f32
    pinned on both sides."""
    import functools

    from blendjax.models.train import TrainState, make_train_step
    from blendjax.parallel import make_ring_attention, seqformer_rules
    from blendjax.parallel.ring_attention import full_attention
    from blendjax.parallel.sharding import make_sharded_train_step
    from jax.sharding import NamedSharding, PartitionSpec as P

    W = 10
    mesh = make_mesh({"data": 2, "seq": 2, "model": 2})
    params = _params()
    batch = _batch(jax.random.PRNGKey(3))
    # sgd, not adam: the windowed ring's per-pair logsumexp combine
    # rounds differently (f32, ~1e-6) than the reference's single
    # softmax, and adam's first step amplifies a sign flip on a
    # near-zero gradient component to a full +-lr — sgd keeps the param
    # delta LINEAR in the gradient difference, so this assert measures
    # gradient agreement, not optimizer chaos
    opt = optax.sgd(1e-2)

    ref_step = make_train_step(
        lambda p, b: seqformer.loss_fn(
            p, b, compute_dtype=jnp.float32,
            attn_fn=lambda q, k, v: full_attention(
                q, k, v, causal=True, window=W
            ),
        ),
        opt,
        donate=False,
    )
    ref_state, ref_loss = ref_step(TrainState.create(params, opt), batch)

    for impl in ("ring", "ring_flash"):
        attn = make_ring_attention(
            mesh, causal=True, impl=impl, batch_axis="data",
            head_axis="model", window=W,
        )
        init_sharded, step = make_sharded_train_step(
            functools.partial(
                seqformer.loss_fn, attn_fn=attn, compute_dtype=jnp.float32
            ),
            opt,
            mesh,
            rules=seqformer_rules("model"),
        )
        state = init_sharded(jax.tree.map(jnp.array, params))
        sharded_batch = jax.device_put(
            batch, NamedSharding(mesh, P("data", "seq", None))
        )
        state, loss = step(state, sharded_batch)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-5
            ),
            state.params,
            ref_state.params,
        )


def test_gqa_model_full_and_flash_agree():
    """n_kv_heads < n_heads (grouped-query attention): the model runs
    through both the default broadcast reference and the flash kernel's
    grouped KV head mapping, and the two agree."""
    from blendjax.ops.flash_attention import make_flash_attention

    params = seqformer.init(
        jax.random.PRNGKey(0), obs_dim=6, d_model=32, n_heads=4,
        n_layers=2, max_len=128, n_kv_heads=2,
    )
    # kv projections really are smaller
    assert params["blocks"][0]["wk"]["w"].shape == (32, 2, 8)
    assert params["blocks"][0]["wq"]["w"].shape == (32, 4, 8)
    obs = jax.random.normal(jax.random.PRNGKey(1), (2, 128, 6), jnp.float32)
    ref = seqformer.apply(params, obs, compute_dtype=jnp.float32)
    flash = seqformer.apply(
        params, obs, compute_dtype=jnp.float32,
        attn_fn=make_flash_attention(causal=True, block_q=64, block_kv=64,
                                     interpret=True),
    )
    np.testing.assert_allclose(
        np.asarray(flash), np.asarray(ref), atol=2e-4, rtol=2e-4
    )

    with pytest.raises(ValueError, match="n_kv_heads"):
        seqformer.init(jax.random.PRNGKey(0), n_heads=4, n_kv_heads=3)


@pytest.mark.parametrize(
    "kwargs,step_kwargs",
    [
        (dict(), dict()),
        (dict(n_kv_heads=2), dict()),
        (dict(n_experts=4), dict(moe_impl="dense")),
        # topk at cf=e/k (drop-free both sides): capacity-bounded
        # routing depends on the TOTAL token count and so cannot match
        # between incremental and full-sequence evaluation — decode is
        # always drop-free (see decode_step), and the reference must be
        # run drop-free too for the comparison to be meaningful
        (dict(n_experts=4),
         dict(moe_impl="topk", moe_k=2, moe_capacity_factor=2.0)),
        (dict(), dict(window=5)),
    ],
    ids=["plain", "gqa", "moe-dense", "moe-topk", "windowed"],
)
def test_rollout_matches_naive_regeneration(kwargs, step_kwargs):
    """The KV-cache rollout must equal the O(T^2) naive approach of
    re-running the full forward on the growing self-fed sequence — for
    the dense, GQA, MoE (both impls), and sliding-window variants."""
    params = seqformer.init(
        jax.random.PRNGKey(0), obs_dim=5, d_model=32, n_heads=4,
        n_layers=2, max_len=32, **kwargs,
    )
    prefix = jax.random.normal(jax.random.PRNGKey(1), (2, 6, 5), jnp.float32)
    n_steps = 4

    got = jax.jit(lambda p, x: seqformer.rollout(
        p, x, n_steps, compute_dtype=jnp.float32,
        cache_dtype=jnp.float32, **step_kwargs,
    ))(params, prefix)
    assert got.shape == (2, n_steps, 5)

    # naive: re-run the teacher-forced forward on the growing sequence
    apply_kwargs = dict(step_kwargs)
    window = apply_kwargs.pop("window", None)
    if window is not None:
        from blendjax.parallel.ring_attention import full_attention

        apply_kwargs["attn_fn"] = lambda q, k, v: full_attention(
            q, k, v, causal=True, window=window
        )
    seq = prefix
    want = []
    for _ in range(n_steps):
        pred = seqformer.apply(
            params, seq, compute_dtype=jnp.float32, **apply_kwargs
        )[:, -1]
        want.append(pred)
        seq = jnp.concatenate([seq, pred[:, None]], axis=1)
    want = jnp.stack(want, axis=1)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-4, rtol=1e-4
    )


def test_rollout_validates_lengths():
    params = seqformer.init(
        jax.random.PRNGKey(0), obs_dim=4, d_model=16, n_heads=2,
        n_layers=1, max_len=8,
    )
    prefix = jnp.zeros((1, 6, 4))
    with pytest.raises(ValueError, match="exceeds max_len"):
        seqformer.rollout(params, prefix, 3)
    with pytest.raises(ValueError, match="n_steps"):
        seqformer.rollout(params, prefix, 0)


def test_rope_scores_are_relative():
    """The rope property the unbounded rollout rests on: shifting every
    position by a constant leaves q·k scores unchanged."""
    from blendjax.models.layers import apply_rope, rope_table

    kq, kk = jax.random.split(jax.random.PRNGKey(0))
    q = jax.random.normal(kq, (1, 8, 2, 16), jnp.float32)
    k = jax.random.normal(kk, (1, 8, 2, 16), jnp.float32)

    def scores(shift):
        cos, sin = rope_table(jnp.arange(8) + shift, 16)
        qr, kr = apply_rope(q, cos, sin), apply_rope(k, cos, sin)
        return jnp.einsum("bqhd,bkhd->bhqk", qr, kr)

    np.testing.assert_allclose(
        np.asarray(scores(0)), np.asarray(scores(1000)), atol=2e-4
    )


def test_rope_model_trains_and_is_causal():
    params = seqformer.init(
        jax.random.PRNGKey(0), obs_dim=OBS, d_model=32, n_heads=4,
        n_layers=2, pos_encoding="rope",
    )
    assert "pos" not in params
    batch = _batch(jax.random.PRNGKey(1))
    out = seqformer.apply(params, batch["obs"], compute_dtype=jnp.float32)
    poked = batch["obs"].at[:, T // 2:].add(100.0)
    out2 = seqformer.apply(params, poked, compute_dtype=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(out[:, : T // 2]), np.asarray(out2[:, : T // 2]),
        atol=1e-5,
    )
    state = TrainState.create(params, optax.adam(1e-2))
    step = make_train_step(
        lambda p, b: seqformer.loss_fn(p, b, compute_dtype=jnp.float32),
        optax.adam(1e-2),
    )
    losses = []
    for _ in range(10):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9


def test_rope_rollout_unbounded_matches_naive():
    """A rope model dreams PAST any learned-table limit (here: horizon
    2x the max_len a learned model of this size would have), and the
    KV-cache rollout still equals naive full-sequence regeneration."""
    params = seqformer.init(
        jax.random.PRNGKey(0), obs_dim=5, d_model=32, n_heads=4,
        n_layers=2, max_len=8, pos_encoding="rope",  # max_len ignored
    )
    prefix = jax.random.normal(jax.random.PRNGKey(1), (2, 6, 5), jnp.float32)
    n_steps = 10  # 6 + 10 = 16 > the (ignored) max_len=8

    got = jax.jit(lambda p, x: seqformer.rollout(
        p, x, n_steps, compute_dtype=jnp.float32, cache_dtype=jnp.float32,
    ))(params, prefix)

    seq = prefix
    want = []
    for _ in range(n_steps):
        pred = seqformer.apply(params, seq, compute_dtype=jnp.float32)[:, -1]
        want.append(pred)
        seq = jnp.concatenate([seq, pred[:, None]], axis=1)
    want = jnp.stack(want, axis=1)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-4
    )


def test_rope_sharded_step_matches_single_device():
    """Rope rotation happens before the attn seam on GLOBAL positions,
    so sequence sharding must not change the numbers."""
    import functools

    from blendjax.parallel import make_ring_attention, seqformer_rules
    from blendjax.parallel.sharding import make_sharded_train_step
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh({"data": 2, "seq": 2, "model": 2})
    params = seqformer.init(
        jax.random.PRNGKey(0), obs_dim=OBS, d_model=32, n_heads=4,
        n_layers=2, pos_encoding="rope",
    )
    batch = _batch(jax.random.PRNGKey(3))
    opt = optax.sgd(1e-2)

    ref_step = make_train_step(
        lambda p, b: seqformer.loss_fn(p, b, compute_dtype=jnp.float32),
        opt, donate=False,
    )
    ref_state, ref_loss = ref_step(TrainState.create(params, opt), batch)

    attn = make_ring_attention(
        mesh, causal=True, impl="ring_flash", batch_axis="data",
        head_axis="model",
    )
    init_sharded, step = make_sharded_train_step(
        functools.partial(
            seqformer.loss_fn, attn_fn=attn, compute_dtype=jnp.float32
        ),
        opt, mesh, rules=seqformer_rules("model"),
    )
    state = init_sharded(jax.tree.map(jnp.array, params))
    state, loss = step(state, jax.device_put(
        batch, NamedSharding(mesh, P("data", "seq", None))
    ))
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5
        ),
        state.params, ref_state.params,
    )


@pytest.mark.parametrize("t0,W", [(6, 8), (9, 4)])
def test_windowed_ring_cache_streams_past_capacity(t0, W):
    """O(window) memory for unbounded dreaming: a rope model dreams 20
    steps through a window-sized ring cache (the horizon wraps the ring
    repeatedly) and still equals naive windowed regeneration.  The
    (9, 4) case has t0 > window, exercising rollout's prefix-tail
    truncation (only the last W prefix positions enter the ring, at
    wrapped slots)."""
    from blendjax.parallel.ring_attention import full_attention

    params = seqformer.init(
        jax.random.PRNGKey(0), obs_dim=5, d_model=32, n_heads=4,
        n_layers=2, pos_encoding="rope",
    )
    prefix = jax.random.normal(jax.random.PRNGKey(1), (2, t0, 5),
                               jnp.float32)
    n_steps = 20

    got = jax.jit(lambda p, x: seqformer.rollout(
        p, x, n_steps, compute_dtype=jnp.float32,
        cache_dtype=jnp.float32, window=W,
    ))(params, prefix)

    attn = lambda q, k, v: full_attention(q, k, v, causal=True, window=W)
    seq = prefix
    want = []
    for _ in range(n_steps):
        pred = seqformer.apply(
            params, seq, compute_dtype=jnp.float32, attn_fn=attn
        )[:, -1]
        want.append(pred)
        seq = jnp.concatenate([seq, pred[:, None]], axis=1)
    want = jnp.stack(want, axis=1)
    # 20 SELF-FED steps amplify f32 rounding differences between the
    # cached and naive paths chaotically; 5e-4 is the open-loop bound,
    # the short-horizon tests assert the tight one
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=5e-4, rtol=5e-4
    )


def test_quantized_seqformer_tracks_float_and_decodes_consistently():
    """int8 w8a8 SeqFormer inference: (a) the quantized teacher-forced
    forward tracks the float one on a TRAINED model; (b) the KV-cache
    rollout on the QUANTIZED pytree still equals naive full-sequence
    regeneration — per-token activation scales keep quantization causal
    (a per-sequence scale would let future positions change a past
    token's quantization and break this)."""
    from blendjax.ops.quant import quantize_seqformer

    params = seqformer.init(
        jax.random.PRNGKey(0), obs_dim=5, d_model=32, n_heads=4,
        n_layers=2, max_len=32,
    )
    batch = seqformer.make_episode_batch(
        jax.random.normal(jax.random.PRNGKey(1), (4, 17, 5), jnp.float32)
    )
    state = TrainState.create(params, optax.adam(1e-2))
    step = make_train_step(
        lambda p, b: seqformer.loss_fn(p, b, compute_dtype=jnp.float32),
        optax.adam(1e-2),
    )
    for _ in range(20):
        state, _ = step(state, batch)
    params = jax.device_get(state.params)

    ref = seqformer.apply(params, batch["obs"], compute_dtype=jnp.float32)
    qparams = quantize_seqformer(params)
    got = seqformer.apply(qparams, batch["obs"], compute_dtype=jnp.float32)
    err = float(jnp.abs(got - ref).max())
    scale = float(jnp.abs(ref).max())
    assert err < 0.05 * max(scale, 1.0), (err, scale)

    # int8 weights dominate the block params
    fb = sum(x.nbytes for x in jax.tree.leaves(params["blocks"]))
    qb = sum(x.nbytes for x in jax.tree.leaves(qparams["blocks"]))
    assert qb < 0.45 * fb

    # (b) incremental == naive ON THE QUANTIZED MODEL
    prefix = batch["obs"][:, :6]
    n_steps = 4
    got_roll = jax.jit(lambda p, x: seqformer.rollout(
        p, x, n_steps, compute_dtype=jnp.float32, cache_dtype=jnp.float32,
    ))(qparams, prefix)
    seq = prefix
    want = []
    for _ in range(n_steps):
        pred = seqformer.apply(qparams, seq,
                               compute_dtype=jnp.float32)[:, -1]
        want.append(pred)
        seq = jnp.concatenate([seq, pred[:, None]], axis=1)
    want = jnp.stack(want, axis=1)
    np.testing.assert_allclose(
        np.asarray(got_roll), np.asarray(want), atol=1e-4, rtol=1e-4
    )


def test_rollout_shards_over_batch_axis():
    """Dreaming composes with data parallelism: a batch-sharded prefix
    rolls out under jit on the mesh and matches the single-device
    rollout (the scan + ring-cache machinery is batch-elementwise, so
    dp sharding is a layout choice here too)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh({"data": 4})
    params = seqformer.init(
        jax.random.PRNGKey(0), obs_dim=5, d_model=32, n_heads=4,
        n_layers=1, pos_encoding="rope",
    )
    prefix = jax.random.normal(jax.random.PRNGKey(1), (8, 6, 5),
                               jnp.float32)

    roll = jax.jit(lambda p, x: seqformer.rollout(
        p, x, 5, compute_dtype=jnp.float32, cache_dtype=jnp.float32,
    ))
    want = roll(params, prefix)
    sharded_prefix = jax.device_put(
        prefix, NamedSharding(mesh, P("data", None, None))
    )
    got = roll(params, sharded_prefix)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-5
    )
