"""Experience replay subsystem: columnar ring storage, prioritized
sampling, ``.btr`` spill/prefill, and the off-policy learner seam.

Opens the off-policy workload family (docs/replay.md): the PR-4
pipelined actor appends transitions while the learner samples batches —
through :class:`~blendjax.btt.arena.ArenaPool` + ``device_prefetch`` on
the device path — and recorded ``.btr`` logs hydrate the buffer so
training runs with zero Blender processes.

Public surface::

    from blendjax.replay import ReplayBuffer, prefill_from_btr

    buf = ReplayBuffer(100_000, seed=0, prioritized=True)
    buf.append({"obs": o, "action": a, "reward": r,
                "next_obs": o2, "done": d}, healthy=True)
    data, idx, w = buf.sample(32)
    buf.update_priorities(idx, errors)
    buf.save("replay.npz"); buf = ReplayBuffer.restore("replay.npz")

The sharded service (docs/replay.md "Sharded replay service") keeps the
same surface over remote storage shards — a drop-in for
``ActorLearner(replay=)`` and ``run_offline`` that survives shard
deaths (quarantine + degraded sampling + crash-exact re-admission)::

    from blendjax.replay import ShardedReplay
    from blendjax.replay.service import ShardFleet

    with ShardFleet(4, capacity_per_shard=25_000, data_dir=d) as fleet:
        buf = ShardedReplay(fleet.addresses, seed=0)
        ...
"""

from blendjax.replay.buffer import HEALTHY_KEY, ReplayBuffer
from blendjax.replay.prefill import (
    iter_btr_transitions,
    message_to_transition,
    prefill_from_btr,
    transition_to_message,
)
from blendjax.replay.ring import ColumnStore
from blendjax.replay.shard_client import (
    ShardClient,
    ShardedReplay,
    ShardRPCError,
)
from blendjax.replay.sumtree import SumTree

__all__ = [
    "HEALTHY_KEY",
    "ReplayBuffer",
    "ColumnStore",
    "SumTree",
    "ShardedReplay",
    "ShardClient",
    "ShardRPCError",
    "prefill_from_btr",
    "iter_btr_transitions",
    "transition_to_message",
    "message_to_transition",
]
