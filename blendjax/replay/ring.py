"""Columnar ring storage for transitions: preallocated per-key numpy
columns with O(1) row append and one-gather-per-key batched reads.

The naive replay layout — a deque of per-transition dicts — pays a dict
+ N array allocations per append and a per-item ``collate`` walk per
sampled batch.  ``ColumnStore`` is the PR-1 arena idea applied to
storage instead of transport: one ``(capacity, *leaf_shape)`` array per
transition key, allocated once on first sight of the schema, rows
written in place (``copy_into`` — GIL released for large leaves, so a
pipelined actor's appends overlap the learner's compute), and batches
gathered column-by-column in ONE native call per key
(:func:`blendjax.native.ring.gather_into`) instead of batch_size
Python-level copies + a stack.

The schema is fixed by the first row: replay is a homogeneous
transition log, so a key that later changes shape/dtype (or appears /
disappears) is a bug upstream and raises instead of degrading — unlike
the wire-facing ``_BatchBuilder``, which must tolerate foreign
producers, every row here was written by this process.

No locking here: the owning :class:`~blendjax.replay.ReplayBuffer`
serializes row writes and gathers together with its index/priority
state (a gather racing a wraparound overwrite would tear rows).
"""

from __future__ import annotations

import numpy as np

from blendjax.native.ring import copy_into, gather_into

#: Rows at or above this many bytes gather via the native GIL-released
#: call; below it, per-source pointer extraction (~3 us/row) costs more
#: than the memcpy saves and ``np.take`` wins.
_NATIVE_GATHER_MIN_BYTES = 16 * 1024


class ColumnStore:
    """Fixed-capacity columnar transition storage.

    Params
    ------
    capacity: int
        Ring size in transitions; row slots are reused modulo capacity
        (the caller owns the head/size bookkeeping).
    """

    def __init__(self, capacity):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.columns = {}  # key -> (capacity, *leaf_shape) ndarray
        self._schema = None  # key -> (shape, dtype), fixed by first row

    def __contains__(self, key):
        return key in self.columns

    @property
    def keys(self):
        return tuple(self.columns)

    @property
    def nbytes(self):
        return sum(c.nbytes for c in self.columns.values())

    def _init_schema(self, row):
        schema = {}
        columns = {}
        for key, value in row.items():
            arr = np.asarray(value)
            if arr.dtype.hasobject or arr.dtype.kind in "USV":
                # strings would coerce to fixed-width unicode and then
                # "drift" on the first longer value — reject upfront
                # (before ANY allocation: a half-built column dict must
                # not leak into a retried append's smaller schema)
                raise TypeError(
                    f"transition key {key!r} has dtype {arr.dtype} "
                    f"({type(value).__name__}); replay columns hold "
                    "fixed-shape numeric/bool arrays only"
                )
            schema[key] = (arr.shape, arr.dtype)
            columns[key] = np.zeros((self.capacity,) + arr.shape, arr.dtype)
        self.columns = columns
        self._schema = schema

    def write_row(self, slot, row):
        """Write one transition dict into ring slot ``slot`` (O(1): a
        memcpy per key into preallocated storage, no allocation)."""
        if self._schema is None:
            self._init_schema(row)
        schema = self._schema
        if row.keys() != schema.keys():
            extra = sorted(set(map(str, row)) ^ set(map(str, schema)))
            raise KeyError(
                f"transition keys changed mid-stream (difference: {extra}); "
                "the replay schema is fixed by the first append"
            )
        for key, (shape, dtype) in schema.items():
            arr = np.asarray(row[key])
            if arr.shape != shape or arr.dtype != dtype:
                raise ValueError(
                    f"transition key {key!r} drifted to "
                    f"{arr.shape}/{arr.dtype} (schema: {shape}/{dtype})"
                )
            col = self.columns[key]
            if shape:
                copy_into(col[slot], np.ascontiguousarray(arr))
            else:
                col[slot] = arr

    def read_row(self, slot):
        """One transition dict, values COPIED out (a view would alias the
        ring slot and mutate under the caller after wraparound)."""
        return {k: np.array(c[slot]) for k, c in self.columns.items()}

    def gather(self, indices, out=None, keys=None):
        """Batched columnar read: ``{key: column[indices]}`` with one
        gather per key.

        ``out`` (optional) supplies preallocated ``(len(indices),
        *shape)`` destinations — either a dict keyed like the columns,
        or a callable ``out(key, shape, dtype) -> ndarray`` (the
        :meth:`blendjax.btt.arena.Arena.get_buffer` signature, so a
        recycled arena plugs in directly) — written in place; otherwise
        fresh arrays are allocated.  Large rows go through the native
        GIL-released ``gather_into`` so a concurrent actor thread keeps
        appending through the copy window.

        ``keys`` (optional) restricts the gather to those columns — a
        consumer that only reads a subset (e.g. an off-policy loss that
        never touches ``next_obs``) skips the copy for the rest.
        """
        idx = np.asarray(indices, np.int64)
        n = idx.size
        if keys is None:
            selected = self.columns
        else:
            missing = [k for k in keys if k not in self.columns]
            if missing:
                raise KeyError(
                    f"no such replay column(s) {missing}; stored keys: "
                    f"{sorted(self.columns)}"
                )
            selected = {k: self.columns[k] for k in keys}
        batch = {}
        for key, col in selected.items():
            row_shape = col.shape[1:]
            if out is None:
                dst = None
            elif callable(out):
                dst = out(key, (n,) + row_shape, col.dtype)
            else:
                dst = out.get(key)
            if dst is not None and (
                dst.shape != (n,) + row_shape or dst.dtype != col.dtype
            ):
                raise ValueError(
                    f"out[{key!r}] is {dst.shape}/{dst.dtype}, need "
                    f"{(n,) + row_shape}/{col.dtype}"
                )
            row_bytes = col[0].nbytes if row_shape else col.itemsize
            if row_shape and row_bytes >= _NATIVE_GATHER_MIN_BYTES:
                if dst is None:
                    dst = np.empty((n,) + row_shape, col.dtype)
                gather_into(dst, [col[i] for i in idx])
            else:
                dst = np.take(col, idx, axis=0, out=dst)
            batch[key] = dst
        return batch

    # -- checkpoint surface --------------------------------------------------

    def state_arrays(self):
        """The raw column arrays, prefixed for a flat checkpoint
        namespace (`col.<key>` -> array)."""
        return {f"col.{k}": v for k, v in self.columns.items()}

    def load_state_arrays(self, arrays):
        """Adopt checkpointed columns (inverse of :meth:`state_arrays`).
        Replaces any existing schema; capacity must match."""
        self.columns = {}
        self._schema = None
        schema = {}
        for name, arr in arrays.items():
            if not name.startswith("col."):
                continue
            key = name[len("col."):]
            if arr.shape[0] != self.capacity:
                raise ValueError(
                    f"checkpoint column {key!r} has capacity "
                    f"{arr.shape[0]}, store expects {self.capacity}"
                )
            self.columns[key] = np.array(arr)  # own the storage
            schema[key] = (arr.shape[1:], arr.dtype)
        if schema:
            self._schema = schema
