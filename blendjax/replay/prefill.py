"""``.btr`` <-> replay interop: hydrate a :class:`ReplayBuffer` from
recorded transition logs so off-policy training runs with ZERO Blender
processes.

The framework's record/replay format (:mod:`blendjax.btt.file`, the
reference's checkpoint/resume analog) already persists raw message
dicts; a *transition* message is simply the transition dict itself plus
the quarantine flag (``healthy``) riding in-band — pickled numpy arrays
round-trip exactly, so a buffer prefilled from a recording is
bit-identical to one fed the same transitions by direct appends (locked
by ``tests/test_replay.py``).

Workflow::

    # live run, recording (fleet side):
    rec = FileRecorder("run_00.btr", max_messages=100000)
    with rec:
        for step in range(n):
            obs2, rew, done, infos = pool.step(actions)
            for i in range(pool.num_envs):
                rec.save(transition_to_message(
                    {"obs": obs[i], "action": actions[i],
                     "reward": rew[i], "next_obs": obs2[i],
                     "done": done[i]},
                    healthy=infos[i].get("healthy", True)))

    # later, no Blender anywhere:
    buf = ReplayBuffer(200000, seed=0)
    n = prefill_from_btr(buf, "run")          # every run_*.btr
    learner.run_offline(num_updates=..., batch_size=...)
"""

from __future__ import annotations

from glob import glob
from pathlib import Path

from blendjax.btt.file import FileReader
from blendjax.replay.buffer import HEALTHY_KEY, SCENARIO_KEY


def transition_to_message(transition, *, healthy=True, scenario=None):
    """Transition dict -> recordable message: the dict itself with the
    quarantine flag in-band under :data:`HEALTHY_KEY` and (when known)
    the scenario id under :data:`SCENARIO_KEY` — both consumed back
    into per-slot bookkeeping by :meth:`ReplayBuffer.append`, so a
    ``.btr``-prefilled buffer is bit-identical (stored bytes AND
    stamps) to one fed the same transitions directly."""
    msg = dict(transition)
    msg[HEALTHY_KEY] = bool(
        msg.get(HEALTHY_KEY, True)
    ) and bool(healthy)
    if scenario is not None and SCENARIO_KEY not in msg:
        msg[SCENARIO_KEY] = str(scenario)
    return msg


def message_to_transition(message):
    """Recorded message -> ``(transition, healthy)``; the inverse of
    :func:`transition_to_message` (the health flag stripped from the
    dict; a :data:`SCENARIO_KEY` stamp stays IN-BAND — ``append``
    consumes it, keeping prefilled stamps identical to live ones)."""
    transition = dict(message)
    healthy = bool(transition.pop(HEALTHY_KEY, True))
    return transition, healthy


def iter_btr_transitions(prefix_or_paths):
    """Yield ``(transition, healthy)`` from ``.btr`` recordings.

    ``prefix_or_paths``: an explicit path / list of paths, or a prefix
    matching ``{prefix}_*.btr`` (the ``FileRecorder.filename`` per-worker
    scheme) — files are visited in sorted order so the append sequence
    is deterministic.
    """
    if isinstance(prefix_or_paths, (str, Path)):
        p = Path(prefix_or_paths)
        if p.exists():
            paths = [p]
        else:
            paths = sorted(glob(f"{prefix_or_paths}_*.btr"))
            if not paths:
                raise FileNotFoundError(
                    f"no .btr file or recordings matching "
                    f"{prefix_or_paths}_*.btr"
                )
    else:
        paths = list(prefix_or_paths)
    for path in paths:
        reader = FileReader(path)
        try:
            for i in range(len(reader)):
                yield message_to_transition(reader[i])
        finally:
            reader.close()


def prefill_from_btr(buffer, prefix_or_paths, *, transform=None, limit=None):
    """Hydrate ``buffer`` from recorded transition logs; returns the
    number of transitions appended.

    ``transform`` (optional) maps each raw message dict to a transition
    dict — use it to adapt recordings whose messages are NOT already
    transition-shaped (e.g. a datagen stream's ``{"image", "xy", ...}``
    frames, or to drop wire bookkeeping keys like ``btid``).  The
    quarantine flag is honored either way: an unhealthy recorded
    transition lands excluded from sampling, exactly as a live
    quarantine-aware append would.  ``limit`` caps the appends (the ring
    evicts oldest-first beyond capacity regardless).
    """
    appended = 0
    for transition, healthy in iter_btr_transitions(prefix_or_paths):
        if limit is not None and appended >= limit:
            break
        if transform is not None:
            transition = transform(transition)
            if transition is None:
                continue
        buffer.append(transition, healthy=healthy)
        appended += 1
    return appended
