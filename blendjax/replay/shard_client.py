"""Sharded replay client: the draw authority over N storage shards.

:class:`ShardedReplay` subclasses :class:`~blendjax.replay.ReplayBuffer`
and keeps EVERY sampling decision local — the global
:class:`~blendjax.replay.sumtree.SumTree`, the seeded RNG, eligibility /
generation masks — while the transition *rows* live on remote
:class:`~blendjax.replay.service.ReplayShard` storage (shard ``s`` owns
global slots ``[s*C, (s+1)*C)``).  Because the draw computation is the
same code over the same tree whatever the layout, the global draw
stream is **bit-identical for any shard count** (1-shard vs 4-shard vs
an in-process ``ReplayBuffer`` with the same capacity and seed — locked
by ``tests/test_replay_service.py``), and ``save``/``restore``
checkpoint the client mid-stream exactly like the base class.

Failure model (docs/fault_tolerance.md vocabulary, pointed at storage):

- every shard RPC runs under a :class:`~blendjax.btt.faults.FaultPolicy`
  (retry with the SAME correlation id — the shard's reply cache makes
  the retry exactly-once — backoff, circuit breaker);
- a shard that exhausts its policy (or whose process the supervisor saw
  die) is **quarantined**: its slot range leaves the draw domain,
  strata renormalize over the live shards' priority mass, and sampling
  continues degraded (``replay_shard_quarantined`` in
  ``REPLAY_EVENTS``); appends owned by the dead shard are **journaled**
  client-side instead of dropped;
- a restarted shard (checkpoint + ``.btr`` spill tail restored) is
  **re-admitted** by a health probe: the client verifies the shard's
  durability cursor against what it acked, flushes the journal, and the
  slot range rejoins the draw domain — the global stream having never
  stopped (``replay_shard_readmissions``).

:class:`~blendjax.btt.supervise.FleetSupervisor` drives both halves
when given a shard launcher (:class:`~blendjax.replay.service.
ShardFleet`) and ``replay=sharded``: deaths quarantine proactively, the
heal thread calls :meth:`ShardedReplay.probe`.
"""

from __future__ import annotations

import logging
import os
import socket as _socket
import threading
import time

import numpy as np

from blendjax import wire
from blendjax.btt.faults import FaultPolicy
from blendjax.obs.flight import flight_recorder
from blendjax.obs.spans import SpanRecorder
from blendjax.replay.buffer import ReplayBuffer, load_client_state
from blendjax.utils.timing import fleet_counters

logger = logging.getLogger("blendjax")

#: Client checkpoint format tag (the shard side uses
#: ``blendjax.replay.shard/1``).
SHARDED_FORMAT = "blendjax.replay.sharded/1"


def free_port():
    """An OS-assigned free TCP port (the usual bind-then-close probe)."""
    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class ReshardAborted(RuntimeError):
    """A live shard handoff (:meth:`ShardedReplay.adopt_shard`) aborted
    WHOLE: the client's ownership map is untouched and the source shard
    keeps serving its full range.  The caller (the autoscale reshard
    orchestrator) retires the would-be shard process."""


class ShardRPCError(TimeoutError):
    """A shard RPC failed at the transport level (no reply within the
    policy, connection refused, circuit open).  Subclasses
    :class:`TimeoutError` so consumers that treat replay starvation as
    skippable (the learner's off-policy tail) handle shard outages the
    same way; carries ``shard_id`` so the failure pins to a shard."""

    def __init__(self, message, shard_id=None):
        super().__init__(message)
        self.shard_id = shard_id


class ShardClient:
    """RPC channel to one replay shard with exactly-once retries.

    Every request is stamped with a fresh ``wire.BTMID_KEY``; a
    fault-policy retry re-sends the SAME id, and replies whose id does
    not match the outstanding request are dropped as stale (a late
    first-attempt reply after a retry, or a dead incarnation's
    leftovers after :meth:`reset_channel`).

    The wire itself is a :class:`~blendjax.btt.transport.RpcChannel`:
    ZMQ DEALER always (control plane + remote fallback), transparently
    upgraded to the ShmRPC ring pair for a same-host shard
    (docs/transport.md).  ``shm=False`` pins the client to ZMQ.
    """

    def __init__(self, address, shard_id=0, *, fault_policy=None,
                 counters=None, timeoutms=5000, context=None,
                 span_recorder=None, shm="auto", shm_chaos=None):
        self.address = address
        self.shard_id = int(shard_id)
        self.policy = fault_policy or FaultPolicy()
        self.state = self.policy.new_state(key=self.shard_id)
        self.counters = counters if counters is not None else fleet_counters
        self.timeoutms = int(timeoutms)
        #: cross-process span sink (None = tracing off): client-side RPC
        #: spans plus the shard's piggybacked server-side spans
        self.spans = span_recorder
        self._ctx = context
        self._shm_mode = shm
        self._shm_chaos = shm_chaos
        self._chan = None

    def _channel(self):
        if self._chan is None:
            from blendjax.btt.transport import RpcChannel

            self._chan = RpcChannel(
                self.address, context=self._ctx, shm=self._shm_mode,
                shm_chaos=self._shm_chaos,
                # zero-copy reply views: every ShardClient reply is
                # consumed before the next RPC (gather scatters into
                # the batch, read_row copies, hellos carry no arrays)
                view_replies=True,
                name=f"replay-shard-{self.shard_id}",
            )
        return self._chan

    @property
    def transport(self):
        """The wire the next RPC rides: ``"shm"`` or ``"tcp"``."""
        return self._chan.transport if self._chan is not None else "tcp"

    def reset_channel(self):
        """Drop the channel (DEALER socket AND any shm ring pair) so
        the next RPC dials fresh — replies a dead shard incarnation
        still manages to emit die with the old channel instead of
        confusing the re-admitted one."""
        if self._chan is not None:
            self._chan.reset()

    close = reset_channel

    def rpc(self, cmd, payload=None, *, timeout_ms=None, raw_buffers=False):
        """One exactly-once RPC under the fault policy; returns the
        decoded reply dict, raises :class:`ShardRPCError` (transport)
        or ``RuntimeError`` (the shard executed and reported failure).
        The retry/stale-reply discipline itself is the shared
        :func:`blendjax.btt.rpc.exactly_once_rpc`."""
        from blendjax.btt.rpc import exactly_once_rpc

        msg = dict(payload or {})
        msg["cmd"] = cmd
        return exactly_once_rpc(
            self._channel, msg,
            policy=self.policy, state=self.state,
            counters=self.counters,
            wait_ms=(self.timeoutms if timeout_ms is None
                     else int(timeout_ms)),
            raw_buffers=raw_buffers, spans=self.spans,
            remote_name=f"replay shard {self.shard_id}",
            span_label=f"shard{self.shard_id}_rpc",
            span_cat="replay_client",
            span_args={"shard": self.shard_id},
            rpc_name=f"replay-shard-{self.shard_id}:{cmd}",
            exc_factory=lambda text: ShardRPCError(
                f"replay shard {self.shard_id} ({self.address}): "
                f"{text}", self.shard_id,
            ),
            retryable=(ShardRPCError,),
        )


class _ShardedStore:
    """The storage half of :class:`ShardedReplay`: the same surface the
    base class uses on its local :class:`~blendjax.replay.ring.
    ColumnStore` (``write_row``/``read_row``/``gather``/checkpoint
    hooks), fanned across shard RPCs.  Schema discipline is identical —
    fixed by the first row, drift raises — enforced client-side so a
    bad append never reaches the wire."""

    def __init__(self, owner):
        self.owner = owner
        self._schema = None  # key -> (shape, dtype)

    @property
    def keys(self):
        return tuple(self._schema) if self._schema else ()

    @property
    def nbytes(self):
        return 0  # rows live on the shards

    def _check_row(self, row):
        if self._schema is None:
            schema = {}
            for key, value in row.items():
                arr = np.asarray(value)
                if arr.dtype.hasobject or arr.dtype.kind in "USV":
                    raise TypeError(
                        f"transition key {key!r} has dtype {arr.dtype} "
                        f"({type(value).__name__}); replay columns hold "
                        "fixed-shape numeric/bool arrays only"
                    )
                schema[key] = (arr.shape, arr.dtype)
            self._schema = schema
            return
        schema = self._schema
        if row.keys() != schema.keys():
            extra = sorted(set(map(str, row)) ^ set(map(str, schema)))
            raise KeyError(
                f"transition keys changed mid-stream (difference: "
                f"{extra}); the replay schema is fixed by the first "
                "append"
            )
        for key, (shape, dtype) in schema.items():
            arr = np.asarray(row[key])
            if arr.shape != shape or arr.dtype != dtype:
                raise ValueError(
                    f"transition key {key!r} drifted to "
                    f"{arr.shape}/{arr.dtype} (schema: {shape}/{dtype})"
                )

    # -- rows ----------------------------------------------------------------

    def write_row(self, slot, row):
        o = self.owner
        self._check_row(row)
        s = int(o._owner[slot])
        if o._dead[s]:
            o._journal_row_locked(slot, row)
            return
        t0 = time.perf_counter()
        try:
            o.clients[s].rpc(
                "append",
                {"rows": [row], "slots": [int(o._local[slot])]},
                raw_buffers=True,
            )
        except ShardRPCError as exc:
            o._quarantine_locked(s, reason=str(exc))
            o._journal_row_locked(slot, row)
            return
        finally:
            o.timer.add("shard_append", time.perf_counter() - t0, _t0=t0)
        o._acked[s] += 1

    def read_row(self, slot):
        o = self.owner
        if o._pending[slot]:
            return {k: np.array(v) for k, v in o._journal[slot].items()}
        out = self.gather(np.array([slot], np.int64))
        return {k: np.array(v[0]) for k, v in out.items()}

    def gather(self, indices, out=None, keys=None):
        o = self.owner
        idx = np.asarray(indices, np.int64)
        n = idx.size
        if self._schema is None:
            raise RuntimeError(
                f"{o.name}: gather before any append fixed the schema"
            )
        if keys is None:
            selected = dict(self._schema)
        else:
            missing = [k for k in keys if k not in self._schema]
            if missing:
                raise KeyError(
                    f"no such replay column(s) {missing}; stored keys: "
                    f"{sorted(self._schema)}"
                )
            selected = {k: self._schema[k] for k in keys}
        batch = {}
        for key, (shape, dtype) in selected.items():
            if out is None:
                dst = np.empty((n,) + shape, dtype)
            elif callable(out):
                dst = out(key, (n,) + shape, dtype)
            else:
                dst = out.get(key)
                if dst is None:
                    dst = np.empty((n,) + shape, dtype)
            if dst.shape != (n,) + shape or dst.dtype != dtype:
                raise ValueError(
                    f"out[{key!r}] is {dst.shape}/{dst.dtype}, need "
                    f"{(n,) + shape}/{dtype}"
                )
            batch[key] = dst
        t0 = time.perf_counter()
        try:
            shard_of = o._owner[idx]
            shards = np.unique(shard_of)
            jobs = []
            for s in shards:
                pos = np.flatnonzero(shard_of == s)
                jobs.append((int(s), pos, o._local[idx[pos]]))
            if len(jobs) > 1 and o._gather_pool is not None:
                # one RPC per shard, in flight CONCURRENTLY: the
                # shards' gathers/ring writes overlap each other (and
                # this thread's scatters) instead of serializing one
                # round trip at a time — most of the wire tax a
                # multi-shard batch still pays after ShmRPC is latency,
                # not bytes
                results = list(o._gather_pool.map(
                    lambda job: self._fetch_shard(job, selected, batch),
                    jobs,
                ))
            else:
                results = [self._fetch_shard(job, selected, batch)
                           for job in jobs]
            for s, exc in results:
                if exc is not None:
                    o._quarantine_locked(s, reason=str(exc))
            for s, exc in results:
                if exc is not None:
                    raise exc
        finally:
            o.timer.add("shard_gather", time.perf_counter() - t0, _t0=t0)
        return batch

    def _fetch_shard(self, job, selected, batch):
        """One shard's slice of a gather: RPC + scatter into the batch
        destinations (disjoint row sets, so concurrent workers never
        overlap).  Returns ``(shard, ShardRPCError | None)`` — the
        quarantine decision stays with the calling thread, which holds
        the buffer lock."""
        s, pos, local = job
        o = self.owner
        try:
            reply = o.clients[s].rpc(
                "gather",
                {"indices": local.tolist(), "keys": list(selected)},
                raw_buffers=True,
            )
        except ShardRPCError as exc:
            return s, exc
        data = reply["data"]
        for key in selected:
            batch[key][pos] = data[key]
        return s, None

    # -- checkpoint surface (storage rides on the shards) --------------------

    def state_arrays(self):
        return {}

    def load_state_arrays(self, arrays):
        pass


class ShardedReplay(ReplayBuffer):
    """Prioritized replay over remote storage shards (see module doc).

    Params (beyond :class:`~blendjax.replay.ReplayBuffer`'s)
    ------
    shards: sequence[str | ShardClient]
        One endpoint (or prepared client) per shard, in slot-range
        order.  Total capacity = ``num_shards * shard_capacity``.
    fault_policy: FaultPolicy | None
        Retry/backoff/circuit policy every shard RPC runs under.  The
        default retries twice with a 5-failure circuit breaker — the
        breaker is what keeps quarantined-shard probes from dialing a
        corpse on every heal tick.
    timeoutms: int
        Per-attempt reply wait.
    shard_capacity: int | None
        Expected per-shard capacity; required (with ``allow_dead``)
        when construction must tolerate an unreachable shard, otherwise
        discovered from the shards' ``hello`` replies (which must
        agree).
    allow_dead: bool
        Quarantine unreachable shards at construction instead of
        raising (the restore-into-a-degraded-deployment path).
    """

    def __init__(self, shards, *, seed=0, prioritized=True, alpha=0.6,
                 beta=0.4, eps=1e-3, counters=None, timer=None,
                 fault_policy=None, timeoutms=5000, name=None,
                 shard_capacity=None, allow_dead=False, context=None,
                 trace=False, span_recorder=None, shm="auto",
                 parallel_gather=None):
        if not shards:
            raise ValueError("ShardedReplay needs at least one shard")
        counters = counters if counters is not None else fleet_counters
        policy = fault_policy or FaultPolicy(
            max_retries=2, backoff_base=0.05, backoff_max=0.5,
            circuit_threshold=5, circuit_cooldown_s=2.0, seed=seed,
        )
        self.fault_policy = policy
        #: cross-process span sink shared by every shard channel (None =
        #: tracing off); shard-side spans piggybacked on replies land
        #: here next to the client RPC spans
        self.spans = (
            span_recorder if span_recorder is not None
            else (SpanRecorder() if trace else None)
        )
        clients = []
        for i, s in enumerate(shards):
            if isinstance(s, ShardClient):
                if s.spans is None:
                    s.spans = self.spans
                clients.append(s)
            else:
                clients.append(ShardClient(
                    s, i, fault_policy=policy, counters=counters,
                    timeoutms=timeoutms, context=context,
                    span_recorder=self.spans, shm=shm,
                ))
        dead_at_init = []
        hellos = []
        for i, c in enumerate(clients):
            try:
                hellos.append(c.rpc("hello"))
            except ShardRPCError:
                if not allow_dead:
                    raise
                hellos.append(None)
                dead_at_init.append(i)
        caps = {int(h["capacity"]) for h in hellos if h is not None}
        if shard_capacity is None:
            if not caps:
                raise ShardRPCError(
                    "every shard unreachable at construction and no "
                    "shard_capacity given"
                )
            if len(caps) != 1:
                raise ValueError(
                    f"shards disagree on capacity: {sorted(caps)}; all "
                    "shards of one ShardedReplay must be equal-sized"
                )
            shard_capacity = caps.pop()
        elif caps and caps != {int(shard_capacity)}:
            raise ValueError(
                f"shards report capacity {sorted(caps)}, expected "
                f"{shard_capacity}"
            )
        self.num_shards = len(clients)
        self.shard_capacity = int(shard_capacity)
        super().__init__(
            self.num_shards * self.shard_capacity, seed=seed,
            prioritized=prioritized, alpha=alpha, beta=beta, eps=eps,
            counters=counters, timer=timer,
            name=name or (
                f"sharded-replay[{len(clients)}x{shard_capacity}]"
            ),
        )
        self.clients = clients
        self.store = _ShardedStore(self)
        #: worker pool for concurrent per-shard gather RPCs (None =
        #: sequential): on by default on multi-core hosts with multiple
        #: shards — the shards' server-side gathers and ring writes
        #: overlap instead of serializing one round trip at a time
        if parallel_gather is None:
            parallel_gather = (
                self.num_shards > 1 and (os.cpu_count() or 1) > 1
            )
        self._gather_pool = None
        if parallel_gather and self.num_shards > 1:
            from concurrent.futures import ThreadPoolExecutor

            self._gather_pool = ThreadPoolExecutor(
                max_workers=min(self.num_shards, 8),
                thread_name_prefix="bjx-shard-gather",
            )
        #: per-shard rows durably acked (the client half of the
        #: crash-exact contract: re-admission verifies the shard's seq
        #: cursor against this)
        self._acked = [
            int(h["seq"]) if h is not None else 0 for h in hellos
        ]
        self._dead = np.zeros(self.num_shards, bool)
        self._pending = np.zeros(self.capacity, bool)
        self._journal = {}  # global slot -> owned row dict
        self._probe_lock = threading.Lock()
        #: slot-range ownership map (the live-resharding seam): global
        #: slot -> owning shard index, and -> its LOCAL slot on that
        #: shard.  The identity layout (shard s owns the contiguous
        #: range [s*C, (s+1)*C) with local = global % C) until a
        #: handoff (:meth:`adopt_shard`) remaps a range onto a new
        #: shard.  Total capacity — and with it the SumTree, the RNG
        #: and every draw — NEVER changes under a reshard: only which
        #: shard serves a slot's storage RPCs does, which is what makes
        #: the draw stream bit-identical across a resize by
        #: construction.
        self._owner = np.repeat(
            np.arange(self.num_shards, dtype=np.int64),
            self.shard_capacity,
        )
        self._local = (np.arange(self.capacity, dtype=np.int64)
                       % self.shard_capacity)
        for h in hellos:
            if h is not None and h.get("keys"):
                # a shard with pre-existing rows: adopt nothing — the
                # client's eligibility state is authoritative and empty,
                # so those rows are plain overwrite targets
                logger.info(
                    "replay shard %s reports %d pre-existing rows",
                    h["shard_id"], h["seq"],
                )
        with self._cond:
            for i in dead_at_init:
                self._quarantine_locked(
                    i, reason="unreachable at construction"
                )

    # -- shard-range helpers -------------------------------------------------

    def _owned_slots(self, s):
        """Global slots shard ``s`` currently owns (contiguous
        ``[s*C, (s+1)*C)`` until a reshard remaps a range)."""
        return np.flatnonzero(self._owner == s)

    def _local_to_global(self, s):
        """Inverse of the ownership map for shard ``s``: its LOCAL slot
        -> the global slot it backs.  Locals are unique per shard (a
        handoff moves a range whose locals were already distinct), so
        the dict is total over owned slots."""
        owned = self._owned_slots(s)
        return {int(self._local[g]): int(g) for g in owned}

    def _eligible_live_locked(self):
        """Mask of rows drawable right now: eligible AND owned by a live
        shard AND not waiting in the journal."""
        live = ~self._dead[self._owner]
        return self._valid & live & ~self._pending

    # -- quarantine / journal / re-admission ---------------------------------

    @property
    def quarantined(self):
        with self._cond:
            return self._dead.copy()

    @property
    def healthy(self):
        with self._cond:
            return ~self._dead

    def _journal_row_locked(self, slot, row):
        # own array leaves (the caller's may view recycled arena/wire
        # memory); immutable scalar leaves ride as-is so their wire
        # encoding matches a direct append's
        self._journal[slot] = {
            k: (np.array(v) if isinstance(v, np.ndarray) else v)
            for k, v in row.items()
        }
        self._pending[slot] = True
        self.counters.incr("replay_shard_journal")

    def _quarantine_locked(self, s, reason="unresponsive"):
        if self._dead[s]:
            return
        self._dead[s] = True
        self.counters.incr("replay_shard_quarantined")
        flight_recorder.note(
            "replay_shard_quarantined", target=f"shard{s}",
            reason=reason, buffer=self.name,
        )
        self.clients[s].reset_channel()
        live = int((~self._dead).sum())
        logger.warning(
            "%s: shard %d quarantined (%s); sampling continues degraded "
            "over %d/%d shards", self.name, s, reason, live,
            self.num_shards,
        )
        self._cond.notify_all()

    def quarantine_shard(self, s, reason="unresponsive"):
        """Isolate shard ``s``: its slot range leaves the draw domain
        (strata renormalize over live shards) and its appends journal
        client-side until re-admission.  Idempotent.  Called by the
        supervisor on shard-process death, and internally when an RPC
        exhausts its fault policy."""
        with self._cond:
            self._quarantine_locked(int(s), reason=reason)

    def notify_respawn(self, s):
        """Clear shard ``s``'s backoff/circuit state so the next
        :meth:`probe` dials it immediately (the supervisor calls this
        right after a successful respawn, mirroring
        ``EnvPool.notify_respawn``)."""
        self.clients[int(s)].state.record_success()

    def probe(self, block_ms=50):
        """Try to re-admit quarantined shards (supervisor heal path; also
        safe to call inline).  Returns True when at least one shard
        rejoined."""
        with self._cond:
            dead = list(np.flatnonzero(self._dead))
        if not dead:
            return False
        readmitted = False
        with self._probe_lock:
            for s in dead:
                client = self.clients[s]
                if client.state.circuit_open():
                    continue
                try:
                    hello = client.rpc("hello", timeout_ms=block_ms)
                except (ShardRPCError, RuntimeError):
                    continue
                with self._cond:
                    if self._readmit_locked(s, hello):
                        readmitted = True
        return readmitted

    def _readmit_locked(self, s, hello):
        if not self._dead[s]:
            return False
        if int(hello["capacity"]) != self.shard_capacity:
            raise RuntimeError(
                f"{self.name}: restarted shard {s} reports capacity "
                f"{hello['capacity']} != {self.shard_capacity}; refusing "
                "re-admission (it would serve wrong rows)"
            )
        shard_seq = int(hello["seq"])
        owned = self._owned_slots(s)
        if shard_seq < self._acked[s]:
            # the shard came back OLDER than what it acked (restored a
            # stale checkpoint with no spill tail): rows in its range
            # may be arbitrarily wrong — invalidate everything except
            # the journal (whose rows we still hold) instead of serving
            # ghost data
            lost = owned[
                self._valid[owned] & ~self._pending[owned]
            ]
            for slot in lost:
                self._valid[slot] = False
                self._num_valid -= 1
                if self.tree is not None:
                    self.tree.set(int(slot), 0.0)
            self.counters.incr("replay_shard_lost", len(lost))
            flight_recorder.note(
                "replay_shard_lost", target=f"shard{s}",
                rows=len(lost), shard_seq=shard_seq, acked=self._acked[s],
                buffer=self.name,
            )
            logger.error(
                "%s: shard %d restored seq %d < acked %d; invalidated "
                "%d rows in its range", self.name, s, shard_seq,
                self._acked[s], len(lost),
            )
        self._acked[s] = max(self._acked[s], shard_seq)
        # flush the journal: rows appended while the shard was down, in
        # slot order (idempotent by content — a lost flush ack re-sends
        # the same rows to the same slots)
        slots = sorted(
            slot for slot in self._journal if self._owner[slot] == s
        )
        if slots:
            try:
                reply = self.clients[s].rpc(
                    "append",
                    {
                        "rows": [self._journal[slot] for slot in slots],
                        "slots": [
                            int(self._local[slot]) for slot in slots
                        ],
                    },
                    raw_buffers=True,
                )
            except ShardRPCError as exc:
                self._quarantine_locked(
                    s, reason=f"journal flush failed: {exc}"
                )
                return False
            self._acked[s] = int(reply["seq"])
            for slot in slots:
                del self._journal[slot]
                self._pending[slot] = False
        self._dead[s] = False
        self.counters.incr("replay_shard_readmissions")
        flight_recorder.note(
            "replay_shard_readmission", target=f"shard{s}",
            seq=self._acked[s], journal_flushed=len(slots),
            buffer=self.name,
        )
        logger.warning(
            "%s: shard %d re-admitted at seq %d (%d journaled rows "
            "flushed); full draw domain restored", self.name, s,
            self._acked[s], len(slots),
        )
        self._cond.notify_all()
        return True

    # -- sampling ------------------------------------------------------------

    def _draw_locked(self, batch_size, beta):
        if not self._dead.any():
            return super()._draw_locked(batch_size, beta)
        return self._draw_degraded_locked(batch_size, beta)

    def _drawable_mask_locked(self):
        """Scenario-strata draws (docs/scenarios.md) honor the same
        degraded-mode eligibility as the base draw: rows on
        quarantined shards or waiting in the journal cannot be
        gathered, so they must not be selected by a stratum either."""
        if not self._dead.any() and not self._pending.any():
            return self._valid
        return self._eligible_live_locked()

    def _draw_degraded_locked(self, batch_size, beta):
        """The degraded draw: strata renormalized over the LIVE,
        drawable priority mass.  The master tree is never mutated by
        quarantine (the dead shards' leaves keep their values for
        re-admission); instead the drawable rows' leaf masses are
        cumulated in slot order and each stratified mass resolved with
        one ``searchsorted`` — exact for ANY capacity.  (The master
        tree's prefix domain cannot be reused here: for non-power-of-2
        capacities the tree's prefix order is a rotation of slot order,
        so shard slot ranges are not contiguous in it.)  O(capacity)
        per draw — the exceptional-outage path trades a vectorized
        cumsum (~0.1 ms at 100k rows) for zero bookkeeping on the hot
        healthy path."""
        eligible = self._eligible_live_locked()
        dead_ids = np.flatnonzero(self._dead)
        if self.tree is not None and self.tree.total > 0.0:
            leaves = self.tree._tree[self.tree.capacity:
                                     self.tree.capacity + self.capacity]
            # journaled rows' mass is masked out too: they cannot be
            # gathered, so it must not distort the strata
            live_mass = np.where(eligible, leaves, 0.0)
            cum = np.cumsum(live_mass)
            live_total = float(cum[-1])
            if live_total > 0.0:
                seg = live_total / batch_size
                masses = (
                    np.arange(batch_size) + self._rng.random(batch_size)
                ) * seg
                masses = np.minimum(
                    masses, np.nextafter(live_total, 0)
                )
                idx = np.minimum(
                    np.searchsorted(cum, masses, side="right"),
                    self.capacity - 1,
                ).astype(np.int64)
                probs = live_mass[idx] / live_total
                # float ties at stratum boundaries can land on a
                # zero-mass leaf: re-route those draws to deterministic
                # uniform picks over the drawable rows
                bad = (probs <= 0.0) | ~eligible[idx]
                if bad.any():
                    pool = np.flatnonzero(eligible)
                    if pool.size == 0:
                        raise TimeoutError(
                            f"{self.name}: no drawable rows outside "
                            f"quarantined shards {list(dead_ids)} "
                            f"({self._diag_locked()})"
                        )
                    idx[bad] = pool[self._rng.integers(
                        0, pool.size, int(bad.sum())
                    )]
                    probs[bad] = 1.0 / pool.size
                n_live = int(eligible.sum())
                weights = (n_live * probs) ** -beta
                weights = (weights / weights.max()).astype(np.float32)
                return idx, weights
        pool = np.flatnonzero(eligible)
        if pool.size == 0:
            raise TimeoutError(
                f"{self.name}: no drawable rows outside quarantined "
                f"shards {list(dead_ids)} ({self._diag_locked()})"
            )
        idx = pool[
            self._rng.integers(0, pool.size, batch_size)
        ].astype(np.int64)
        return idx, np.ones(batch_size, np.float32)

    def sample(self, batch_size, **kwargs):
        """Base-class :meth:`~blendjax.replay.ReplayBuffer.sample`, plus
        the storage failure path: a shard dying mid-gather is
        quarantined and the draw retried over the survivors — one
        degraded redraw per newly-dead shard, then the error surfaces
        naming the shard and embedding :meth:`stats`."""
        last = None
        for _ in range(self.num_shards + 1):
            try:
                return super().sample(batch_size, **kwargs)
            except ShardRPCError as exc:
                if exc.shard_id is None:
                    raise
                last = exc
        raise ShardRPCError(
            f"{self.name}: sampling failed even after quarantining "
            f"shard {last.shard_id} ({last}; {self._diag()})",
            last.shard_id,
        )

    # -- checkpoint ----------------------------------------------------------

    def _state_arrays_meta_locked(self):
        arrays, meta = super()._state_arrays_meta_locked()
        arrays["pending"] = self._pending
        arrays["owner"] = self._owner
        arrays["local"] = self._local
        for slot, row in self._journal.items():
            for key, value in row.items():
                arrays[f"jrn.{slot}.{key}"] = value
        meta["format"] = SHARDED_FORMAT
        meta["num_shards"] = self.num_shards
        meta["shard_capacity"] = self.shard_capacity
        meta["acked"] = [int(a) for a in self._acked]
        meta["dead"] = [int(s) for s in np.flatnonzero(self._dead)]
        meta["schema"] = {
            k: [list(shape), np.dtype(dtype).str]
            for k, (shape, dtype) in (self.store._schema or {}).items()
        }
        return arrays, meta

    def save(self, path):
        """Checkpoint the sampling authority AND snapshot every live
        shard, under one lock so client state and shard contents agree
        (appends block for the duration).  Restoring the pair continues
        the exact draw stream — the base-class contract, now spanning
        the service."""
        from blendjax.utils.checkpoint import save_state

        with self._cond:
            arrays, meta = self._state_arrays_meta_locked()
            snapshots = {}
            for s, client in enumerate(self.clients):
                if self._dead[s]:
                    snapshots[str(s)] = None
                    continue
                reply = client.rpc("save")
                snapshots[str(s)] = {
                    "path": reply.get("path"), "seq": int(reply["seq"]),
                }
            meta["shard_snapshots"] = snapshots
            save_state(path, arrays, meta)
        return path

    @classmethod
    def restore(cls, path, shards, *, counters=None, timer=None,
                fault_policy=None, timeoutms=5000, allow_dead=True,
                context=None, reconcile=False):
        """Rebuild the sampling authority from :meth:`save` output over
        ``shards`` (typically the same deployment, restarted).  Each
        reachable shard's durability cursor must match what the
        checkpoint acked — a shard that restored different contents
        than this client state describes would serve wrong rows, so the
        mismatch raises instead.  Unreachable shards start quarantined
        (``allow_dead``) and re-admit through the normal probe path.

        ``reconcile=True`` is the **learner-failover** mode
        (docs/fault_tolerance.md "Learner failover"): the shards
        SURVIVED while their client died, so a shard legitimately sits
        AHEAD of the checkpoint — the dead client appended rows after
        the cut.  Each such shard is asked ``written_since(acked)`` and
        exactly the slots written past the cut are invalidated
        client-side (counted ``replay_shard_lost``): they hold rows the
        rewound draw state does not describe, and the resumed actors
        rewrite them in the same ring order — the *replayed* rung of
        the recovery-semantics table.  A shard that cannot answer
        exactly (tail rotated/overflowed past the cut) has its whole
        range rolled back instead of trusting a partial list.  A shard
        BEHIND the checkpoint still raises — that is real data loss,
        not a rewound client."""
        from blendjax.utils.checkpoint import load_state

        arrays, meta = load_state(path)
        fmt = meta.get("format")
        if fmt != SHARDED_FORMAT:
            raise ValueError(
                f"not a sharded replay checkpoint (format {fmt!r})"
            )
        buf = cls(
            shards, seed=meta["seed"], prioritized=meta["prioritized"],
            alpha=meta["alpha"], beta=meta["beta"], eps=meta["eps"],
            counters=counters, timer=timer, fault_policy=fault_policy,
            timeoutms=timeoutms,
            shard_capacity=int(meta["shard_capacity"]),
            allow_dead=allow_dead, context=context,
        )
        if buf.num_shards != int(meta["num_shards"]):
            raise ValueError(
                f"checkpoint spans {meta['num_shards']} shards, "
                f"{buf.num_shards} endpoints given"
            )
        load_client_state(buf, arrays, meta)
        buf.store._schema = {
            k: (tuple(shape), np.dtype(dt))
            for k, (shape, dt) in (meta.get("schema") or {}).items()
        }
        buf._pending = np.array(arrays["pending"], bool)
        if "owner" in arrays:
            # resharded deployments carry an explicit slot-ownership map;
            # older checkpoints predate it and keep the identity layout
            # __init__ already built
            buf._owner = np.array(arrays["owner"], np.int64)
            buf._local = np.array(arrays["local"], np.int64)
        for arr_name, value in arrays.items():
            if not arr_name.startswith("jrn."):
                continue
            _, slot, key = arr_name.split(".", 2)
            buf._journal.setdefault(int(slot), {})[key] = np.array(value)
        acked = [int(a) for a in meta["acked"]]
        meta_dead = {int(s) for s in meta.get("dead", [])}
        for s in range(buf.num_shards):
            if buf._dead[s]:
                buf._acked[s] = acked[s]
                continue
            if s in meta_dead:
                # quarantined at checkpoint time: no snapshot exists for
                # it and its cursor may legitimately run ahead of the
                # stale ack (a durably-applied append whose ack was
                # lost triggered the quarantine) — it goes back through
                # the re-admission handshake below, which reconciles
                # the cursors and invalidates anything unaccounted
                buf._acked[s] = max(buf._acked[s], acked[s])
                continue
            shard_seq = buf._acked[s]  # hello's cursor from __init__
            if shard_seq > acked[s] and reconcile:
                buf._reconcile_ahead_shard(s, acked[s])
                continue
            if shard_seq != acked[s]:
                raise RuntimeError(
                    f"{buf.name}: shard {s} is at seq {shard_seq} but "
                    f"the checkpoint acked {acked[s]} — restore the "
                    "shard from its matching snapshot before restoring "
                    "the client (or pass reconcile=True for the "
                    "learner-failover case of a live shard ahead of a "
                    "rewound client), or it would serve rows the draw "
                    "state does not describe"
                )
        for s in meta_dead:
            with buf._cond:
                buf._quarantine_locked(
                    int(s), reason="quarantined at checkpoint time"
                )
        return buf

    def _reconcile_ahead_shard(self, s, acked_at_cut):
        """Restore-time reconcile of a live shard AHEAD of the client
        checkpoint (see :meth:`restore` ``reconcile=``): invalidate the
        slots written past the cut so the rewound draw state never
        gathers rows it does not describe."""
        inv = self._local_to_global(s)
        reply = self.clients[s].rpc(
            "written_since", {"seq": int(acked_at_cut)}
        )
        if reply["complete"]:
            targets = [
                inv[int(slot)] for slot in reply["slots"]
                if int(slot) in inv
            ]
            reason = f"{len(targets)} slots written past the cut"
        else:
            targets = [int(g) for g in self._owned_slots(s)]
            reason = (
                "tail rotated/overflowed past the cut; whole range "
                "rolled back"
            )
        with self._cond:
            rolled = 0
            for slot in targets:
                if not self._valid[slot] or self._pending[slot]:
                    continue
                self._valid[slot] = False
                self._num_valid -= 1
                if self.tree is not None:
                    self.tree.set(int(slot), 0.0)
                rolled += 1
            # the shard's post-cut rows ARE durable — the acked cursor
            # tracks the shard's real seq so resumed appends stay in
            # sync; only the DRAW domain rolled back to the cut
            self._acked[s] = int(reply["seq"])
        if rolled:
            self.counters.incr("replay_shard_lost", rolled)
        flight_recorder.note(
            "replay_shard_reconciled", target=f"shard{s}",
            rolled_back=rolled, acked_at_cut=int(acked_at_cut),
            shard_seq=int(reply["seq"]), buffer=self.name,
        )
        logger.warning(
            "%s: shard %d reconciled ahead of the checkpoint cut "
            "(seq %d > acked %d): %s; %d rows left the draw domain "
            "until the resumed actors rewrite them", self.name, s,
            int(reply["seq"]), int(acked_at_cut), reason, rolled,
        )

    # -- live resharding -----------------------------------------------------

    def adopt_shard(self, new_shard, *, source, cut_seq, fraction=0.5,
                    timeoutms=5000):
        """Admit a NEW storage shard by handing it a slot range from a
        live ``source`` shard — the replay half of live autoscaling
        (docs/autoscaling.md "Shard handoff").

        The caller has already (1) checkpointed the source at
        ``cut_seq`` (its ``save`` RPC) and (2) spawned ``new_shard``
        restored FROM that checkpoint (:meth:`~blendjax.replay.service.
        ShardFleet.grow` with ``restore_ckpt=``), so the new shard
        holds every source row up to the cut.  This method verifies
        that, copies only the rows the source appended PAST the cut
        into the moving range (reconciled via ``written_since`` — the
        same machinery re-admission trusts), and flips ownership of the
        upper ``fraction`` of the source's slots under the buffer lock
        (appends block for the cutover, draws never stop).

        Total capacity, the SumTree and the RNG are untouched: draws
        over unmoved ranges are bit-identical, draws over moved ranges
        gather the same rows from a different process.

        ABORTS WHOLE on any verification or copy failure
        (:class:`ReshardAborted`, ``autoscale_reshard_aborts``): the
        ownership map is untouched, the source keeps serving its full
        range, and the caller retires the would-be shard.  The source
        is never quarantined by a handoff failure — direct RPCs here
        bypass the write-path quarantine machinery on purpose.

        Params
        ------
        new_shard: str | ShardClient
            Endpoint (or prepared client) of the restored new shard.
        source: int
            Live shard index surrendering a slot range.
        cut_seq: int
            The source's durability cursor at the checkpoint the new
            shard restored (``save`` RPC's ``seq``).
        fraction: float
            Fraction of the source's owned slots to move (upper end of
            its owned range; defaults to an even split).

        Returns the new shard's index.
        """
        s = int(source)
        cut_seq = int(cut_seq)
        t0 = time.perf_counter()
        if isinstance(new_shard, ShardClient):
            client = new_shard
            if client.spans is None:
                client.spans = self.spans
        else:
            client = ShardClient(
                new_shard, self.num_shards,
                fault_policy=self.fault_policy, counters=self.counters,
                timeoutms=timeoutms, span_recorder=self.spans,
            )

        def _abort(why, exc=None):
            self.counters.incr("autoscale_reshard_aborts")
            flight_recorder.note(
                "autoscale_reshard_aborted", target=f"shard{s}",
                reason=why, buffer=self.name,
            )
            client.reset_channel()
            logger.error(
                "%s: shard handoff from %d aborted (%s); ownership map "
                "untouched, source keeps serving", self.name, s, why,
            )
            err = ReshardAborted(f"{self.name}: shard handoff aborted: {why}")
            if exc is not None:
                raise err from exc
            raise err

        # phase 1 (unlocked): verify the new shard restored the cut
        try:
            hello = client.rpc("hello")
        except ShardRPCError as exc:
            _abort(f"new shard unreachable: {exc}", exc)
        if int(hello["capacity"]) != self.shard_capacity:
            _abort(
                f"new shard capacity {hello['capacity']} != "
                f"{self.shard_capacity}"
            )
        if int(hello["seq"]) != cut_seq:
            _abort(
                f"new shard restored seq {hello['seq']}, expected the "
                f"cut at {cut_seq} (wrong/stale checkpoint)"
            )

        # phase 2 (locked): appends block while ownership flips; draws
        # keep flowing the moment the lock drops
        with self._cond:
            if s < 0 or s >= self.num_shards:
                _abort(f"no such source shard {s}")
            if self._dead[s]:
                _abort(f"source shard {s} is quarantined")
            owned = self._owned_slots(s)
            k = int(len(owned) * float(fraction))
            if k < 1 or k >= len(owned):
                _abort(
                    f"fraction {fraction} of {len(owned)} owned slots "
                    "leaves nothing to move (or nothing behind)"
                )
            moved = owned[len(owned) - k:]
            if self._pending[moved].any():
                _abort("journaled rows in the moving range")
            # rows the source appended past the cut: exactly these are
            # missing from the checkpoint the new shard restored
            try:
                since = self.clients[s].rpc(
                    "written_since", {"seq": cut_seq}
                )
            except ShardRPCError as exc:
                _abort(f"source written_since failed: {exc}", exc)
            if not since["complete"]:
                _abort(
                    "source cannot enumerate rows past the cut (tail "
                    "rotated); re-checkpoint and retry"
                )
            inv = self._local_to_global(s)
            moving = set(int(g) for g in moved)
            delta = sorted({
                int(slot) for slot in since["slots"]
                if int(slot) in inv and inv[int(slot)] in moving
            })
            new_seq = cut_seq
            if delta:
                keys = list(self.store._schema or {})
                if not keys:
                    _abort(
                        f"{len(delta)} rows past the cut but no schema "
                        "fixed client-side (state mismatch)"
                    )
                try:
                    got = self.clients[s].rpc(
                        "gather", {"indices": delta, "keys": keys},
                        raw_buffers=True,
                    )
                    rows = [
                        {key: got["data"][key][i] for key in keys}
                        for i in range(len(delta))
                    ]
                    reply = client.rpc(
                        "append", {"rows": rows, "slots": delta},
                        raw_buffers=True,
                    )
                except ShardRPCError as exc:
                    _abort(f"delta copy failed: {exc}", exc)
                new_seq = int(reply["seq"])
            # commit: the new shard joins the draw domain owning the
            # moved range; everything before this line was reversible
            t = self.num_shards
            client.shard_id = t
            self.clients.append(client)
            self.num_shards = t + 1
            self._dead = np.append(self._dead, False)
            self._acked.append(int(new_seq))
            self._owner[moved] = t
            self._cond.notify_all()
        dt = time.perf_counter() - t0
        self.timer.add("autoscale_handoff", dt, _t0=t0)
        self.counters.incr("autoscale_reshard_handoffs")
        self.counters.incr("autoscale_reshard_rows_copied", len(delta))
        flight_recorder.note(
            "autoscale_reshard_handoff", target=f"shard{t}",
            source=s, moved=len(moved), copied=len(delta),
            cut_seq=cut_seq, buffer=self.name,
        )
        logger.warning(
            "%s: shard %d adopted %d slots from shard %d (%d rows "
            "copied past the cut, %.3fs); draw stream continuous",
            self.name, t, len(moved), s, len(delta), dt,
        )
        return t

    # -- observability -------------------------------------------------------

    def shard_telemetry(self, s, timeout_ms=500):
        """One shard process's telemetry snapshot (the jax-free shard's
        ``telemetry`` RPC: counters + per-stage latency histograms in
        the TelemetryHub merge shape).  Raises :class:`ShardRPCError`
        for a dead/quarantined shard — the hub reports that as a
        ``remote_errors`` entry instead of failing the scrape."""
        with self._cond:
            if self._dead[s]:
                raise ShardRPCError(
                    f"shard {s} is quarantined", int(s)
                )
        return self.clients[int(s)].rpc("telemetry", timeout_ms=timeout_ms)

    def register_with_hub(self, hub, name=None):
        """Wire this buffer into a :class:`~blendjax.obs.TelemetryHub`:
        the client's counters + stage timer locally, and every shard
        process as a remote telemetry source (pulled per scrape over
        the existing RPC channel)."""
        name = name or self.name
        hub.register(
            name, counters=self.counters, timer=self.timer,
            probe=self.stats,
        )
        for s in range(self.num_shards):
            hub.register_remote(
                f"{name}/shard{s}",
                lambda s=s: self.shard_telemetry(s),
            )
        return hub

    def _diag_locked(self):
        dead = list(np.flatnonzero(self._dead))
        return (
            super()._diag_locked()
            + f" shards={self.num_shards} quarantined={dead} "
            f"journal={int(self._pending.sum())}"
        )

    def stats(self):
        st = super().stats()
        with self._cond:
            st["shards"] = {
                "count": self.num_shards,
                "capacity_per_shard": self.shard_capacity,
                "quarantined": [
                    int(s) for s in np.flatnonzero(self._dead)
                ],
                "acked": [int(a) for a in self._acked],
                "journal_pending": int(self._pending.sum()),
                "addresses": [c.address for c in self.clients],
                "owned_slots": [
                    int((self._owner == s).sum())
                    for s in range(self.num_shards)
                ],
            }
        return st

    def close(self):
        if self._gather_pool is not None:
            self._gather_pool.shutdown(wait=False)
            self._gather_pool = None
        for c in self.clients:
            c.close()
