"""Fixed-capacity experience replay over a columnar ring store.

The decoupling PR 4 could not give the learner: with the pipelined
actor, learner throughput is still chained to live Blender physics
because every transition is consumed once and discarded.  A
``ReplayBuffer`` breaks the chain (Podracer architectures,
arXiv:2104.06272): the actor appends transitions at fleet rate, the
learner samples batches at device rate, and the two meet only at this
buffer's lock.

Design points (see docs/replay.md):

- **columnar ring** (:class:`~blendjax.replay.ring.ColumnStore`): one
  preallocated ``(capacity, *shape)`` array per transition key — O(1)
  appends with zero per-transition allocation, batches gathered one
  native GIL-released call per key;
- **prioritized sampling** (:class:`~blendjax.replay.sumtree.SumTree`):
  ``P(i) = p_i^alpha / sum p^alpha`` with importance-sampling weights
  ``w_i = (N * P(i))^-beta / max_j w_j`` (Schaul et al. 2015); new
  transitions enter at the running max priority so nothing is starved
  before its first draw; ``prioritized=False`` degrades to uniform over
  the eligible rows (weights identically 1);
- **seeded determinism**: one ``numpy.random.Generator`` drives every
  draw; same seed + same append sequence -> identical sample streams,
  and :meth:`save`/:meth:`restore` checkpoint the generator state along
  with columns + sum tree, so a restored buffer continues the exact
  stream it would have produced;
- **quarantine awareness**: appends flagged unhealthy (synthetic
  degraded-mode transitions from a quarantined env — see
  docs/fault_tolerance.md) are stored but excluded from sampling (tree
  priority 0 and masked out of the uniform path) and counted under
  ``replay_excluded``;
- **thread safety**: one lock serializes row writes, index/priority
  state, and gathers (a gather racing a wraparound overwrite would tear
  rows); the GIL-released native copies keep the hold time to the
  memcpy itself.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from blendjax.replay.ring import ColumnStore
from blendjax.replay.sumtree import SumTree
from blendjax.utils.timing import StageTimer, fleet_counters

#: Transition key reserved for the quarantine flag: consumed into the
#: eligibility mask at append time, never stored as a column (so a
#: ``.btr``-prefilled buffer is bit-identical to one fed by direct
#: appends — the flag travels inside the recorded message).
HEALTHY_KEY = "healthy"

#: Transition key reserved for the scenario id (docs/scenarios.md):
#: same in-band pattern as :data:`HEALTHY_KEY` — consumed into a
#: per-slot stamp at append time, never stored as a column, and it
#: travels inside recorded ``.btr`` messages so a prefilled buffer's
#: stamps (and stored bytes) are bit-identical to direct appends.
#: Stamps feed per-scenario strata (:meth:`ReplayBuffer.scenario_stats`
#: and the ``scenario_mix=`` draw shaping) and never touch the RNG or
#: the sum tree on their own, so a stamped-but-unmixed buffer draws the
#: exact scenario-less stream.
SCENARIO_KEY = "scenario"


def load_client_state(buf, arrays, meta):
    """Apply checkpointed sampling state (eligibility masks, generations,
    sum tree, ring indices, RNG) to a freshly-constructed buffer —
    shared by :meth:`ReplayBuffer.restore` and the sharded client's
    restore, whose storage lives on remote shards instead of in
    ``arrays``."""
    buf._valid = np.array(arrays["valid"], bool)
    buf._healthy = np.array(arrays["healthy"], bool)
    if "gen" in arrays:
        buf._gen = np.array(arrays["gen"], np.int64)
        buf._drawn_gen = np.array(arrays["drawn_gen"], np.int64)
    if "scenario" in arrays:
        # scenario stamps + the id<->name interning table (older
        # checkpoints carry neither: every slot restores unlabelled)
        buf._scenario = np.array(arrays["scenario"], np.int32)
        buf._scenario_names = list(meta.get("scenario_names", []))
        buf._scenario_ids = {
            n: i for i, n in enumerate(buf._scenario_names)
        }
    if buf.tree is not None:
        buf.tree.rebuild(arrays["tree_leaves"])
    buf._head = int(meta["head"])
    buf._size = int(meta["size"])
    buf._num_valid = int(meta["num_valid"])
    buf._max_priority = float(meta["max_priority"])
    buf._appends = int(meta["appends"])
    buf._overwrites = int(meta["overwrites"])
    buf._excluded = int(meta["excluded"])
    buf._samples = int(meta["samples"])
    state = meta["rng_state"]
    buf._rng = np.random.default_rng()
    try:
        buf._rng.bit_generator.state = state
    except (ValueError, TypeError):
        # a foreign bit generator (checkpoint written under a numpy
        # whose default generator differs): rebuild it by name
        bg = getattr(np.random, state["bit_generator"])()
        bg.state = state
        buf._rng = np.random.Generator(bg)
    return buf


class ReplayBuffer:
    """Thread-safe prioritized experience replay.

    Params
    ------
    capacity: int
        Ring size in transitions; at capacity the oldest row is evicted
        per append.
    seed: int
        Seeds the sampling RNG (deterministic draw stream).
    prioritized: bool
        Sum-tree proportional sampling with IS weights; False = uniform.
    alpha: float
        Priority exponent (0 = uniform even when prioritized).
    beta: float
        IS-weight exponent (1 = full bias correction).
    eps: float
        Additive floor inside ``(|p| + eps)^alpha`` so zero-error
        transitions keep non-zero mass.
    counters: EventCounters | None
        Sink for ``REPLAY_EVENTS``; defaults to the process-wide
        ``fleet_counters`` so ``FleetSupervisor.health()`` sees them.
    timer: StageTimer | None
        Records ``replay_append`` / ``sample_wait`` / ``sample_gather``
        / ``priority_update`` stages; a private timer is created when
        omitted (always inspectable via ``buffer.timer``).
    name: str | None
        Label this buffer carries in every error it raises (a degraded
        run's traceback must identify WHICH buffer/shard starved without
        log archaeology — the errors also embed a :meth:`stats`
        digest).  Defaults to ``replay[<capacity>]``.
    """

    def __init__(self, capacity, *, seed=0, prioritized=True, alpha=0.6,
                 beta=0.4, eps=1e-3, counters=None, timer=None, name=None):
        self.capacity = int(capacity)
        self.name = name or f"replay[{self.capacity}]"
        self.prioritized = bool(prioritized)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.eps = float(eps)
        self.seed = int(seed)
        self.store = ColumnStore(capacity)
        self.tree = SumTree(capacity) if self.prioritized else None
        self.counters = counters if counters is not None else fleet_counters
        self.timer = timer if timer is not None else StageTimer()
        self._rng = np.random.default_rng(seed)
        self._cond = threading.Condition()
        self._valid = np.zeros(self.capacity, bool)   # eligible for sampling
        self._healthy = np.ones(self.capacity, bool)  # quarantine flags
        # per-slot write generation, and the generation each slot carried
        # when it was last drawn: update_priorities refuses a slot whose
        # row was overwritten after its draw (the stale magnitude belongs
        # to the evicted transition, not the new occupant)
        self._gen = np.zeros(self.capacity, np.int64)
        self._drawn_gen = np.full(self.capacity, -1, np.int64)
        # per-slot scenario stamp (-1 = unlabelled) + the string<->int
        # interning table; stamps are pure bookkeeping — they never
        # touch the RNG or the tree, so the draw stream of a stamped
        # buffer is bit-identical to an unstamped one unless a
        # NON-uniform ``scenario_mix`` explicitly shapes a draw
        self._scenario = np.full(self.capacity, -1, np.int32)
        self._scenario_names = []
        self._scenario_ids = {}
        self._head = 0
        self._size = 0
        self._num_valid = 0
        self._max_priority = 1.0  # tree-space (already exponentiated)
        # local mirrors of the shared counters, for stats()/health()
        self._appends = 0
        self._overwrites = 0
        self._excluded = 0
        self._samples = 0

    def __len__(self):
        with self._cond:
            return self._size

    @property
    def num_eligible(self):
        """Rows currently eligible for sampling (healthy, live)."""
        with self._cond:
            return self._num_valid

    # -- error diagnostics ---------------------------------------------------

    def _diag_locked(self):
        """One-line stats digest for exception messages (caller holds the
        lock; the lock is not reentrant).  A TimeoutError in a degraded
        run must be diagnosable from the traceback alone (docs/replay.md),
        so every starvation/shard error embeds this."""
        return (
            f"size={self._size}/{self.capacity} eligible={self._num_valid} "
            f"excluded={self._excluded} appends={self._appends} "
            f"overwrites={self._overwrites} samples={self._samples}"
        )

    def _diag(self):
        with self._cond:
            return self._diag_locked()

    # -- append side ---------------------------------------------------------

    def _tree_priority(self, priority):
        """Map a caller-space priority (|TD error|-like magnitude) into
        tree space: ``(|p| + eps)^alpha``."""
        return float(abs(priority) + self.eps) ** self.alpha

    def _scenario_id_locked(self, scenario):
        """Intern a scenario name (caller holds the lock); -1 for None."""
        if scenario is None:
            return -1
        sid = self._scenario_ids.get(scenario)
        if sid is None:
            sid = len(self._scenario_names)
            self._scenario_names.append(str(scenario))
            self._scenario_ids[str(scenario)] = sid
        return sid

    def append(self, transition, *, healthy=True, priority=None,
               scenario=None):
        """Append one transition dict (O(1), no allocation after the
        first row fixes the schema).  Returns the ring slot written.

        A ``transition[HEALTHY_KEY]`` bool (as written by
        :func:`~blendjax.replay.prefill.transition_to_message`) is
        consumed into the flag rather than stored; the ``healthy``
        kwarg ANDs with it.  Unhealthy rows are stored (inspectable via
        :meth:`get`) but never sampled.  A ``transition[SCENARIO_KEY]``
        string (or the ``scenario`` kwarg; the in-band value wins) is
        consumed into the slot's scenario stamp the same way —
        docs/scenarios.md — feeding the per-scenario strata without
        becoming a stored column.

        ``priority``: caller-space magnitude for prioritized mode; new
        rows default to the running max so they are sampled at least
        once before their first priority update.
        """
        if HEALTHY_KEY in transition or SCENARIO_KEY in transition:
            transition = dict(transition)
            if HEALTHY_KEY in transition:
                healthy = bool(transition.pop(HEALTHY_KEY)) \
                    and bool(healthy)
            if SCENARIO_KEY in transition:
                inband = transition.pop(SCENARIO_KEY)
                if inband is not None:
                    scenario = inband
        t0 = time.perf_counter()
        with self._cond:
            slot = self._head
            evicting = self._size == self.capacity
            self.store.write_row(slot, transition)
            self._head = (slot + 1) % self.capacity
            if not evicting:
                self._size += 1
            elif self._valid[slot]:
                self._overwrites += 1
                self.counters.incr("replay_overwrites")
                self._num_valid -= 1
            elif not self._healthy[slot]:
                self._excluded -= 1  # evicted an excluded row
            self._healthy[slot] = healthy
            self._valid[slot] = healthy
            sid = self._scenario_id_locked(scenario)
            self._scenario[slot] = sid
            if sid >= 0:
                self.counters.incr("scenario_rows_stamped")
            self._gen[slot] += 1
            if healthy:
                self._num_valid += 1
            else:
                self._excluded += 1
                self.counters.incr("replay_excluded")
            if self.tree is not None:
                if not healthy:
                    self.tree.set(slot, 0.0)
                else:
                    p = (
                        self._max_priority
                        if priority is None
                        else self._tree_priority(priority)
                    )
                    self._max_priority = max(self._max_priority, p)
                    self.tree.set(slot, p)
            self._appends += 1
            self.counters.incr("replay_appends")
            self._cond.notify_all()
        self.timer.add("replay_append", time.perf_counter() - t0, _t0=t0)
        return slot

    def extend(self, transitions, *, healthy=None, scenarios=None):
        """Append a sequence of transition dicts; ``healthy`` is an
        optional parallel bool sequence (e.g. the pool's per-env health
        mask for one step) and ``scenarios`` an optional parallel
        scenario-name sequence (e.g. the per-env stamps one fleet step
        produced)."""
        for i, tr in enumerate(transitions):
            self.append(
                tr,
                healthy=True if healthy is None else bool(healthy[i]),
                scenario=None if scenarios is None else scenarios[i],
            )

    def get(self, index):
        """One stored transition (values copied out), including excluded
        rows — diagnostics and the naive-sampling baseline."""
        with self._cond:
            if not 0 <= index < self._size:
                raise IndexError(index)
            return self.store.read_row(index)

    # -- sample side ---------------------------------------------------------

    def _draw_locked(self, batch_size, beta):
        """Draw indices + IS weights under the lock (deterministic RNG
        order: one draw call per sample call)."""
        if self.tree is not None and self.tree.total > 0.0:
            total = self.tree.total
            # stratified: one uniform per equal-mass segment, so a batch
            # spans the priority range instead of clustering on the mode
            seg = total / batch_size
            masses = (np.arange(batch_size) + self._rng.random(batch_size)) * seg
            idx = self.tree.prefix_search_batch(
                np.minimum(masses, np.nextafter(total, 0))
            )
            probs = self.tree.get_many(idx) / total
            # float-edge descents can land on a zero-mass leaf; re-route
            # them to deterministic uniform picks over the eligible rows
            bad = probs <= 0.0
            if bad.any():
                eligible = np.flatnonzero(self._valid)
                idx[bad] = eligible[
                    self._rng.integers(0, eligible.size, int(bad.sum()))
                ]
                probs[bad] = 1.0 / self._num_valid
            weights = (self._num_valid * probs) ** -beta
            weights = (weights / weights.max()).astype(np.float32)
        else:
            eligible = np.flatnonzero(self._valid)
            idx = eligible[
                self._rng.integers(0, eligible.size, batch_size)
            ].astype(np.int64)
            weights = np.ones(batch_size, np.float32)
        return idx, weights

    def _drawable_mask_locked(self):
        """Rows drawable RIGHT NOW (caller holds the lock).  The base
        buffer draws from every eligible row; :class:`ShardedReplay`
        overrides this to exclude quarantined-shard and journaled rows,
        so the scenario-strata draw honors the same degraded-mode
        eligibility its base draw does."""
        return self._valid

    def _effective_mix_locked(self, scenario_mix):
        """Resolve a requested scenario mix to the strata the draw can
        actually honor (caller holds the lock), or None for the base
        draw path.

        None and UNIFORM mixes resolve to None — the scenario-less
        identity, byte-identical on the draw stream by construction
        (the regression-locked contract: scenario plane off, or on at
        uniform, changes nothing).  Scenarios with no eligible rows are
        dropped and the rest renormalized (degraded strata, the same
        spirit as shard-outage renormalization); a mix with NO
        satisfiable stratum also falls back to the base path rather
        than starving the learner."""
        if not scenario_mix:
            return None
        drawable = self._drawable_mask_locked()
        vals = [float(v) for v in scenario_mix.values()]
        if max(vals) - min(vals) < 1e-12:
            # uniform — the identity, but ONLY when it spans every
            # drawable row (the curriculum's uniform mix always names
            # the whole catalog).  An equal-weight PARTIAL mix (e.g.
            # one scenario pinned alone) genuinely restricts the draw
            # and must take the strata path.
            ids = [self._scenario_ids[n] for n in scenario_mix
                   if n in self._scenario_ids]
            if not drawable.any() or np.isin(
                self._scenario[drawable], ids
            ).all():
                return None
        live = {}
        for name, w in scenario_mix.items():
            if w <= 0:
                continue
            sid = self._scenario_ids.get(name)
            if sid is None:
                continue
            if bool((drawable
                     & (self._scenario == sid)).any()):
                live[name] = float(w)
        if not live:
            return None
        total = sum(live.values())
        return {n: w / total for n, w in live.items()}

    def _draw_strata_locked(self, batch_size, beta, mix):
        """Scenario-stratified draw (non-uniform mix only): batch rows
        apportioned per stratum (largest remainder, mix order), drawn
        within each stratum by the stratum's own tree-priority mass
        (uniform inside a stratum when unprioritized).  IS weights use
        the true under-mix sampling probability
        ``P(i) = mix[s] * p_i / mass_s``, so the PER bias correction
        stays exact under the reweighted draw."""
        from blendjax.scenario.curriculum import apportion

        drawable = self._drawable_mask_locked()
        counts = {}
        for name in apportion(mix, batch_size):
            counts[name] = counts.get(name, 0) + 1
        idx_parts, prob_parts = [], []
        for name in mix:
            k = counts.get(name, 0)
            if k == 0:
                continue
            sid = self._scenario_ids[name]
            slots = np.flatnonzero(drawable & (self._scenario == sid))
            if self.tree is not None:
                p = self.tree.get_many(slots.astype(np.int64))
                mass = float(p.sum())
                probs = (p / mass) if mass > 0 else np.full(
                    slots.size, 1.0 / slots.size
                )
            else:
                probs = np.full(slots.size, 1.0 / slots.size)
            pick = self._rng.choice(slots.size, size=k, p=probs)
            idx_parts.append(slots[pick].astype(np.int64))
            prob_parts.append(mix[name] * probs[pick])
        idx = np.concatenate(idx_parts)
        probs = np.concatenate(prob_parts)
        weights = (self._num_valid * np.maximum(probs, 1e-12)) ** -beta
        weights = (weights / weights.max()).astype(np.float32)
        self.counters.incr("scenario_strata_draws")
        return idx, weights

    def sample(self, batch_size, *, beta=None, min_size=None, timeout=30.0,
               out=None, stop_event=None, keys=None, scenario_mix=None):
        """Draw one prioritized (or uniform) batch.

        Returns ``(data, indices, weights)``: ``data`` is a dict of
        ``(batch_size, *shape)`` arrays gathered column-by-column (into
        ``out`` buffers when given — e.g. an arena's), ``indices`` are
        the ring slots (feed them back to :meth:`update_priorities`),
        ``weights`` the normalized IS weights (all ones when uniform).
        ``keys`` restricts the gather (and any device transfer behind
        it) to the columns the consumer actually reads.

        ``scenario_mix`` (docs/scenarios.md): a name->weight dict
        shapes the draw over per-scenario strata — rows apportioned
        per stratum, drawn within each by its own priority mass, IS
        weights corrected for the reweighting.  ``None`` and UNIFORM
        mixes take the exact scenario-less draw path (byte-identical
        stream — the scenario plane's no-op contract, regression
        locked); strata with no eligible rows are dropped and the rest
        renormalized.

        Blocks while fewer than ``min_size`` (default ``batch_size``)
        eligible rows exist — the learner outpacing the actor — timed
        under the ``sample_wait`` stage; raises TimeoutError after
        ``timeout`` seconds, returns None if ``stop_event`` fires.
        """
        need = batch_size if min_size is None else max(min_size, 1)
        deadline = time.monotonic() + timeout
        with self._cond:
            if self._num_valid < need:
                t0 = time.perf_counter()
                waited = False
                while self._num_valid < need:
                    if stop_event is not None and stop_event.is_set():
                        self.timer.add(
                            "sample_wait", time.perf_counter() - t0, _t0=t0
                        )
                        return None
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        self.timer.add(
                            "sample_wait", time.perf_counter() - t0, _t0=t0
                        )
                        raise TimeoutError(
                            f"{self.name}: underfilled — {self._num_valid} "
                            f"eligible rows < {need} after {timeout:.1f}s "
                            f"({self._diag_locked()})"
                        )
                    if not waited:
                        # counted only when the call actually blocks — a
                        # deliberate timeout=0 probe (the learner's
                        # non-blocking off-policy tail) is not a "wait"
                        waited = True
                        self.counters.incr("replay_sample_waits")
                    self._cond.wait(min(0.1, remaining))
                self.timer.add("sample_wait", time.perf_counter() - t0, _t0=t0)
            t0 = time.perf_counter()
            mix = self._effective_mix_locked(scenario_mix)
            if mix is None:
                idx, weights = self._draw_locked(
                    batch_size, self.beta if beta is None else beta
                )
            else:
                idx, weights = self._draw_strata_locked(
                    batch_size, self.beta if beta is None else beta, mix
                )
            self._drawn_gen[idx] = self._gen[idx]
            data = self.store.gather(idx, out=out, keys=keys)
            self._samples += 1
            self.counters.incr("replay_samples")
        self.timer.add("sample_gather", time.perf_counter() - t0, _t0=t0)
        return data, idx, weights

    def update_priorities(self, indices, priorities):
        """Refresh sampled rows' priorities from fresh learner error
        magnitudes (caller space; ``(|p| + eps)^alpha`` applied here).

        Rows excluded since the draw are skipped, and so are rows whose
        slot was OVERWRITTEN after its last draw (generation check —
        the stale magnitude would otherwise land on an unrelated new
        occupant).  A slot never drawn at all (since construction or
        restore) accepts a direct priority set; once a slot has been
        drawn, updates apply only while the drawn row is still the
        occupant — a wrapped slot's new row rides its entering (max)
        priority until its own first draw re-arms updates (a stale
        update and a direct set are indistinguishable from here, so
        both are refused).  The one window left open: a slot
        overwritten and then re-drawn by a concurrent prefetched batch
        before this update applies accepts the stale value — bounded
        and self-correcting, since the later batch's own update follows
        with the fresh magnitude."""
        if self.tree is None:
            return
        t0 = time.perf_counter()
        with self._cond:
            for i, p in zip(np.asarray(indices, np.int64),
                            np.asarray(priorities, np.float64)):
                if not self._valid[i]:
                    continue
                if self._drawn_gen[i] >= 0 and \
                        self._gen[i] != self._drawn_gen[i]:
                    continue  # overwritten since its last draw

                tp = self._tree_priority(float(p))
                self._max_priority = max(self._max_priority, tp)
                self.tree.set(int(i), tp)
            self.counters.incr("replay_priority_updates")
        self.timer.add("priority_update", time.perf_counter() - t0, _t0=t0)

    def sample_batches(self, batch_size, *, arena_pool=None, beta=None,
                       stop_event=None, timeout=30.0, keys=None,
                       scenario_mix=None):
        """Generator of sampled batches for the device feed: each batch
        is gathered straight into a recycled
        :class:`~blendjax.btt.arena.Arena` when ``arena_pool`` is given
        and yielded as an :class:`~blendjax.btt.arena.ArenaBatch` whose
        ``meta`` carries ``(indices, weights)`` — drain it through
        ``device_prefetch`` and the arena recycles after each transfer
        completes, exactly like the PR-1 feed path.  ``is_weight`` and
        ``replay_idx`` also ride INSIDE the batch dict (the device
        prefetcher unwraps ArenaBatch, so in-band is how they reach a
        prefetched consumer).  Without a pool, plain dicts are yielded.
        """
        from blendjax.btt.arena import ArenaBatch

        while stop_event is None or not stop_event.is_set():
            arena = None
            out = None
            if arena_pool is not None:
                with self.timer.stage("arena_wait"):
                    arena = arena_pool.acquire(
                        timeout=timeout, stop_event=stop_event
                    )
                if arena is None:
                    if stop_event is not None and stop_event.is_set():
                        return
                    # pool exhaustion is a stalled consumer, not end of
                    # data — ending the stream here would let an offline
                    # run truncate silently (same contract as the feed
                    # path's _acquire_arena)
                    raise TimeoutError(
                        f"{self.name}: no batch arena freed within "
                        f"{timeout:.1f}s (pool size "
                        f"{arena_pool.pool_size}); the consumer has "
                        "stalled or the pool is undersized "
                        f"({self._diag()})"
                    )
                # bind lazily per key (the Arena.get_buffer signature):
                # the schema may not even exist yet while sample() blocks
                # on the first appends
                out = arena.get_buffer
            try:
                res = self.sample(
                    batch_size, beta=beta, out=out,
                    stop_event=stop_event, timeout=timeout, keys=keys,
                    scenario_mix=scenario_mix,
                )
            except BaseException:
                if arena is not None:
                    arena.release()
                raise
            if res is None:
                if arena is not None:
                    arena.release()
                return
            data, idx, weights = res
            data = dict(data)
            data["replay_idx"] = idx
            data["is_weight"] = weights
            if arena is not None:
                yield ArenaBatch(data, arena, meta=(idx, weights))
            else:
                yield data

    # -- checkpoint ----------------------------------------------------------

    def _state_arrays_meta_locked(self):
        """The checkpointable client state (caller holds the lock) —
        shared by :meth:`save` and the sharded subclass, which swaps the
        format tag and rides shard bookkeeping alongside."""
        arrays = dict(self.store.state_arrays())
        arrays["valid"] = self._valid
        arrays["healthy"] = self._healthy
        arrays["gen"] = self._gen
        arrays["drawn_gen"] = self._drawn_gen
        arrays["scenario"] = self._scenario
        if self.tree is not None:
            arrays["tree_leaves"] = self.tree.leaves()
        meta = {
            "scenario_names": list(self._scenario_names),
            "format": "blendjax.replay/1",
            "capacity": self.capacity,
            "head": self._head,
            "size": self._size,
            "num_valid": self._num_valid,
            "seed": self.seed,
            "prioritized": self.prioritized,
            "alpha": self.alpha,
            "beta": self.beta,
            "eps": self.eps,
            "max_priority": self._max_priority,
            "appends": self._appends,
            "overwrites": self._overwrites,
            "excluded": self._excluded,
            "samples": self._samples,
            "rng_state": self._rng.bit_generator.state,
        }
        return arrays, meta

    def save(self, path):
        """Checkpoint buffer contents + sum tree + RNG state (atomic;
        :func:`blendjax.utils.checkpoint.save_state`)."""
        from blendjax.utils.checkpoint import save_state

        with self._cond:
            arrays, meta = self._state_arrays_meta_locked()
            save_state(path, arrays, meta)
        return path

    @classmethod
    def restore(cls, path, *, counters=None, timer=None):
        """Rebuild a buffer from :meth:`save` output: columns, ring
        indices, sum tree, and the RNG mid-stream — the restored buffer
        produces the exact sample stream the saved one would have."""
        from blendjax.utils.checkpoint import load_state

        arrays, meta = load_state(path)
        fmt = meta.get("format")
        if fmt != "blendjax.replay/1":
            raise ValueError(f"not a replay checkpoint (format {fmt!r})")
        buf = cls(
            meta["capacity"], seed=meta["seed"],
            prioritized=meta["prioritized"], alpha=meta["alpha"],
            beta=meta["beta"], eps=meta["eps"],
            counters=counters, timer=timer,
        )
        buf.store.load_state_arrays(arrays)
        load_client_state(buf, arrays, meta)
        return buf

    # -- observability -------------------------------------------------------

    def scenario_stats(self):
        """Per-scenario strata snapshot (docs/scenarios.md): for every
        interned scenario, its live ``rows``, sampling-``eligible``
        rows, and ``priority_mass`` (sum of its eligible rows' tree
        priorities — the TD-error evidence the
        :class:`~blendjax.scenario.CurriculumScheduler` reweights on;
        the eligible count itself when unprioritized).  ``_unlabelled``
        rows ride under that key so the strata always account for every
        occupied slot.  Computed on demand — stamps cost nothing on the
        append/draw hot paths, and a buffer with NO stamps at all
        returns ``{}`` without touching the arrays (a scenario-less
        deployment's periodic health scrape stays O(1) here)."""
        with self._cond:
            if not self._scenario_names:
                return {}
            occupied = np.zeros(self.capacity, bool)
            occupied[:self._size] = True
            leaves = self.tree.leaves() if self.tree is not None else None
            out = {}
            for sid in range(-1, len(self._scenario_names)):
                mask = occupied & (self._scenario == sid)
                rows = int(mask.sum())
                if sid < 0 and rows == 0:
                    continue  # fully-labelled buffer: no _unlabelled row
                eligible = mask & self._valid
                name = ("_unlabelled" if sid < 0
                        else self._scenario_names[sid])
                out[name] = {
                    "rows": rows,
                    "eligible": int(eligible.sum()),
                    "priority_mass": float(
                        leaves[eligible].sum() if leaves is not None
                        else eligible.sum()
                    ),
                }
            return out

    def stats(self):
        """One snapshot for ``FleetSupervisor.health()``: fill state,
        exclusion accounting, and the replay stage timings."""
        scenarios = self.scenario_stats()
        with self._cond:
            return {
                "scenarios": scenarios,
                "name": self.name,
                "size": self._size,
                "capacity": self.capacity,
                "eligible": self._num_valid,
                "excluded": self._excluded,
                "appends": self._appends,
                "overwrites": self._overwrites,
                "samples": self._samples,
                "prioritized": self.prioritized,
                "priority_total": (
                    self.tree.total if self.tree is not None else None
                ),
                "stages": self.timer.summary(),
            }
