"""Sum tree for O(log n) prioritized sampling (Schaul et al. 2015,
"Prioritized Experience Replay", arXiv:1511.05952).

A flat-array binary tree over ``capacity`` leaves: internal node ``i``
holds the sum of its children ``2i``/``2i+1``, leaves live at
``[capacity, 2*capacity)``.  ``set`` updates one leaf and its ancestors;
``prefix_search(m)`` descends from the root to the leaf where the
running prefix sum crosses ``m`` — sampling a leaf with probability
``p_i / total`` takes one uniform draw plus one descent.

Pure numpy, no locking: the owning :class:`~blendjax.replay.ReplayBuffer`
serializes access (the tree and the ring columns must mutate under one
lock anyway, or a sampled index could dangle past a wraparound evict).
"""

from __future__ import annotations

import numpy as np


class SumTree:
    """Fixed-capacity sum tree over non-negative leaf priorities."""

    __slots__ = ("capacity", "_tree")

    def __init__(self, capacity):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        # float64 throughout: a float32 running sum drifts after ~1e7
        # incremental updates and prefix_search then dereferences leaves
        # whose true mass is zero
        self._tree = np.zeros(2 * self.capacity, np.float64)

    @property
    def total(self):
        """Sum of all leaf priorities (the sampling normalizer)."""
        return float(self._tree[1])

    def get(self, idx):
        """Priority of leaf ``idx``."""
        return float(self._tree[self.capacity + idx])

    def leaves(self):
        """Copy of all leaf priorities, index-aligned with the ring."""
        return self._tree[self.capacity:].copy()

    def set(self, idx, priority):
        """Set leaf ``idx`` to ``priority`` (>= 0), refreshing ancestors."""
        if priority < 0 or not np.isfinite(priority):
            raise ValueError(f"priority must be finite and >= 0: {priority}")
        i = self.capacity + int(idx)
        delta = float(priority) - self._tree[i]
        if delta == 0.0:
            return
        while i >= 1:
            self._tree[i] += delta
            i >>= 1

    def set_many(self, indices, priorities):
        """Vectorized :meth:`set` over index/priority arrays."""
        priorities = np.asarray(priorities, np.float64)
        if priorities.size and (
            (priorities < 0).any() or not np.isfinite(priorities).all()
        ):
            raise ValueError("priorities must be finite and >= 0")
        for idx, p in zip(np.asarray(indices, np.int64), priorities):
            self.set(int(idx), float(p))

    def prefix_search(self, mass):
        """Leaf index where the running prefix sum first exceeds ``mass``.

        ``mass`` must lie in ``[0, total)``; the descent clamps against
        float round-off at the last leaf so a draw of ``total - eps``
        cannot fall off the end.
        """
        tree = self._tree
        i = 1
        while i < self.capacity:
            left = 2 * i
            if mass < tree[left]:
                i = left
            else:
                mass -= tree[left]
                i = left + 1
        return i - self.capacity

    def get_many(self, indices):
        """Vectorized :meth:`get`: priorities of ``indices`` leaves."""
        return self._tree[self.capacity + np.asarray(indices, np.int64)]

    def prefix_search_batch(self, masses):
        """Vectorized :meth:`prefix_search` over an array of masses.

        One level-synchronous descent: every mass walks down in lockstep
        with numpy ops per level instead of a Python loop per mass.  For
        non-power-of-two capacities leaves sit at mixed depths, so each
        element freezes (``active`` mask) as soon as its node index
        crosses into leaf territory.  Bit-identical to the scalar
        descent — same comparisons, same float subtraction order — so a
        draw stream is unchanged by batching.
        """
        tree = self._tree
        m = np.array(masses, np.float64)
        i = np.ones(m.shape, np.int64)
        active = i < self.capacity
        while active.any():
            left = 2 * i
            # inactive lanes read node 1 (harmless) to keep the take legal
            lv = tree[np.where(active, left, 1)]
            go_left = active & (m < lv)
            go_right = active & ~go_left
            m = np.where(go_right, m - lv, m)
            i = np.where(go_left, left, np.where(go_right, left + 1, i))
            active = i < self.capacity
        return i - self.capacity

    def rebuild(self, leaf_priorities):
        """Reinitialize every leaf at once (checkpoint restore): one
        bottom-up pass instead of ``capacity`` ancestor walks."""
        leaves = np.asarray(leaf_priorities, np.float64)
        if leaves.shape != (self.capacity,):
            raise ValueError(
                f"expected {self.capacity} leaf priorities, got {leaves.shape}"
            )
        if leaves.size and ((leaves < 0).any() or not np.isfinite(leaves).all()):
            raise ValueError("priorities must be finite and >= 0")
        self._tree[self.capacity:] = leaves
        # level-synchronous bottom-up: internal nodes [2^k, 2^{k+1}) hold
        # children strictly deeper, so each level is one vectorized add —
        # log2(capacity) numpy ops instead of a capacity-sized Python
        # loop (restore at 1M leaves: ~ms, not ~0.5s)
        tree = self._tree
        top = (self.capacity - 1).bit_length() - 1 if self.capacity > 1 else -1
        for k in range(top, -1, -1):
            lo = 1 << k
            hi = min(lo << 1, self.capacity)
            tree[lo:hi] = (
                tree[2 * lo:2 * hi:2] + tree[2 * lo + 1:2 * hi:2]
            )
