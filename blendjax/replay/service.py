"""Replay shard service: one durable :class:`ColumnStore` behind the wire.

ROADMAP #2 promotes :class:`~blendjax.replay.ReplayBuffer` from an
in-process object to the system's **storage layer**: a sharded, tiered
service actors and learners reach over the wire, whose failures are
handled with the same ``FaultPolicy``/quarantine vocabulary the EnvPool
speaks (Podracer architectures, arXiv:2104.06272, assume exactly this
tier).  The split of responsibilities:

- a **shard** (this module) is *storage + durability*: a columnar ring
  (:class:`~blendjax.replay.ring.ColumnStore`) served over the existing
  DEALER<->REP wire protocol, every accepted append journaled to a
  ``.btr`` spill log (the cold tier — :class:`~blendjax.btt.file.
  FileRecorder`, flushed **before** the ack, so an acked row survives a
  SIGKILL the next instant) and periodically checkpointed atomically
  (:func:`blendjax.utils.checkpoint.save_state`).  Restart = load the
  latest checkpoint, replay the spill tail (crash-tolerant
  :func:`~blendjax.btt.file.scan_messages` scan), serve — bit-identical
  pre-crash contents;
- the **client** (:class:`~blendjax.replay.shard_client.ShardedReplay`)
  owns every sampling decision: the global sum tree, the seeded RNG,
  eligibility/generation masks.  Shards therefore never need to agree
  on a draw, and a dead shard costs exactly its slot range — see
  docs/replay.md ("Sharded replay service").

Exactly-once RPCs: the client stamps every request with a
``wire.BTMID_KEY`` correlation id and a fault-policy retry re-sends the
SAME id; the shard answers a retried mutating request (``append``,
``save``) from a bounded reply cache instead of applying it twice —
the ``RemoteControlledAgent`` reply-cache pattern, pointed at storage.

Run a shard as a process (jax-free, fast start)::

    python -m blendjax.replay.service --address tcp://127.0.0.1:23000 \
        --capacity 65536 --shard-id 0 --dir /data/replay \
        --checkpoint-every 4096

or in-process for tests/benchmarks via :func:`start_shard_thread`, or
as a supervised fleet via :class:`ShardFleet` (a launcher-compatible
surface, so :class:`~blendjax.btt.supervise.FleetSupervisor` respawns
dead shard processes and drives the client's re-admission probes).
"""

from __future__ import annotations

import argparse
import glob
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from collections import OrderedDict, deque

import numpy as np

from blendjax import wire
from blendjax.btt import shm_rpc
from blendjax.btt.file import FileRecorder, scan_messages
from blendjax.obs.spans import make_span, now_us
from blendjax.replay.ring import ColumnStore
from blendjax.utils.timing import StageTimer, fleet_counters

logger = logging.getLogger("blendjax")

#: Checkpoint format tag (shard side; the client checkpoint carries
#: ``blendjax.replay.sharded/1``).
SHARD_FORMAT = "blendjax.replay.shard/1"

#: Spill-log capacity per file when auto-checkpointing is off.  A spill
#: that fills forces a checkpoint (rotating to a fresh file) rather
#: than dropping records — the append ack promises durability — so this
#: also bounds the recovery-replay tail.  Kept moderate because the
#: ``.btr`` header is a pickled int64 offsets array of this length,
#: written at open and rewritten at close (8 bytes/slot of header I/O
#: per rotation).
SPILL_CAPACITY = 65536

#: Bound on the in-memory (seq, slot) tail mirror behind the
#: ``written_since`` RPC.  At the cap, the oldest entry evicts and the
#: tail's completeness floor rises to its seq — a query below the
#: floor reports INCOMPLETE and the client rolls the whole shard range
#: back instead of trusting a partial answer.
TAIL_SLOTS_CAP = 65536


class ReplayShard:
    """One replay storage shard: columnar ring + spill log + checkpoints,
    served over a REP socket.

    Params
    ------
    address: str
        Endpoint to bind.  ``tcp://host:*`` binds an ephemeral port;
        the resolved endpoint is available as :attr:`address`.
    capacity: int
        Ring slots this shard owns.
    shard_id: int
        Identity reported in ``hello`` replies and used in on-disk
        names (``shard_{id:02d}.*``).
    data_dir: str | None
        Durability root.  None disables both tiers (a pure in-memory
        shard — fine for benchmarks, no crash recovery).
    checkpoint_every: int
        Auto-checkpoint after this many appends since the last one
        (0 = only on explicit ``save`` RPCs).  The spill log rotates at
        every checkpoint, so recovery replays a bounded tail.
    counters: EventCounters | None
        Sink for ``record_drops`` etc.; defaults to the process-wide
        ``fleet_counters``.
    shm_base: str | None
        ``/dev/shm`` name prefix for this shard's ShmRPC transport
        (``--shm-base``): supervised fleets pass one so the PARENT can
        sweep leaked objects after a SIGKILL (docs/transport.md).
        Generated when None.  The transport itself only exists when
        :func:`blendjax.btt.shm_rpc.enabled` (kill-switch
        ``BJX_NO_SHM_RPC=1`` pins the shard to pure ZMQ).
    """

    def __init__(self, address, capacity, *, shard_id=0, data_dir=None,
                 checkpoint_every=0, counters=None, context=None,
                 shm_base=None):
        import zmq

        self.shard_id = int(shard_id)
        self.capacity = int(capacity)
        self.data_dir = data_dir
        self.checkpoint_every = int(checkpoint_every)
        self.counters = counters if counters is not None else fleet_counters
        #: server-side stage timer (``shard_srv_<cmd>`` per request, with
        #: latency histograms) — shipped to clients by the ``telemetry``
        #: RPC so a consumer-side TelemetryHub can merge this process's
        #: percentiles without any exporter running here
        self.timer = StageTimer()
        self.store = ColumnStore(self.capacity)
        #: total rows ever accepted (the durability cursor: checkpoint
        #: meta and spill records carry it, restore resumes from it)
        self.seq = 0
        self._last_ckpt_seq = 0
        self.restored_from = None  # (ckpt_seq, tail_records) after restore
        #: (seq, slot) of recent appends — the in-memory mirror behind
        #: the ``written_since`` RPC (learner-failover restore
        #: reconciles a rewound client against the slots written past
        #: its cut; see docs/fault_tolerance.md "Learner failover").
        #: Retained ACROSS checkpoints — a client's cut can predate the
        #: shard's latest checkpoint (the learner died between a
        #: barrier's shard save and its manifest commit) and the query
        #: must still answer.  ``_tail_floor`` is the durability cursor
        #: the tail is complete back to: it rises only when the bounded
        #: deque evicts (or on process restart, where appends before
        #: the restored checkpoint are unknowable) — a query below the
        #: floor is honestly incomplete instead of wrong.
        self._tail_slots = deque()
        self._tail_floor = 0
        self._spill = None
        if data_dir is not None:
            os.makedirs(data_dir, exist_ok=True)
            self._restore_from_disk()
            self._open_spill()
        self._reply_cache = OrderedDict()  # mid -> reply (mutating cmds)
        self._gather_bufs = {}  # recycled gather-reply buffers (shm path)
        self._reply_synchronous = False  # True while serving an shm request
        self._ctx = context or zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.REP)
        self._sock.setsockopt(zmq.LINGER, 0)
        if address.endswith(":*") or address.endswith(":0"):
            base = address.rsplit(":", 1)[0]
            port = self._sock.bind_to_random_port(base)
            self.address = f"{base}:{port}"
        else:
            self._sock.bind(address)
            self.address = address
        #: same-host shm transport (None when disabled/unavailable):
        #: the ZMQ socket stays the control plane and remote fallback
        self._shm = None
        if shm_rpc.enabled():
            self._shm = shm_rpc.ShmRpcServer(
                base=shm_base or shm_rpc.new_base(f"rs{self.shard_id}"),
                counters=self.counters, bytes_counter="replay_shm_bytes",
                who=f"replay shard {self.shard_id}",
            )

    @property
    def shm_endpoint(self):
        """The advertised ``shm://`` endpoint (None on pure-ZMQ shards)."""
        return self._shm.endpoint if self._shm is not None else None

    # -- durability ----------------------------------------------------------

    def _ckpt_path(self):
        return os.path.join(
            self.data_dir, f"shard_{self.shard_id:02d}.ckpt.npz"
        )

    def _spill_paths(self):
        return sorted(glob.glob(os.path.join(
            self.data_dir, f"shard_{self.shard_id:02d}.spill-*.btr"
        )))

    def _open_spill(self):
        path = os.path.join(
            self.data_dir,
            f"shard_{self.shard_id:02d}.spill-{self.seq:012d}.btr",
        )
        # header cost is 8 bytes per slot at open AND close: size the
        # file to its actual rotation interval instead of a worst case
        cap = (
            max(1024, 4 * self.checkpoint_every)
            if self.checkpoint_every > 0 else SPILL_CAPACITY
        )
        self._spill = FileRecorder(
            path, max_messages=cap, counters=self.counters
        ).__enter__()

    def _restore_from_disk(self):
        """Latest checkpoint + spill tail -> exact pre-crash contents."""
        from blendjax.utils.checkpoint import load_state

        ckpt = self._ckpt_path()
        if os.path.exists(ckpt):
            arrays, meta = load_state(ckpt)
            if meta.get("format") != SHARD_FORMAT:
                raise ValueError(
                    f"{ckpt} is not a replay shard checkpoint "
                    f"(format {meta.get('format')!r})"
                )
            if int(meta["capacity"]) != self.capacity:
                raise ValueError(
                    f"shard {self.shard_id}: checkpoint capacity "
                    f"{meta['capacity']} != configured {self.capacity}"
                )
            self.store.load_state_arrays(arrays)
            self.seq = int(meta["seq"])
            self._last_ckpt_seq = self.seq
            # appends before the restored checkpoint left no tail
            # record; the spill replay below re-adds everything newer
            self._tail_floor = self.seq
        tail = 0
        for path in self._spill_paths():
            # scan, never FileReader: a killed shard's spill has an
            # unfinalized header, and the tail past the checkpoint is
            # exactly the data a crash would otherwise lose
            for rec in scan_messages(path):
                if int(rec["seq"]) <= self.seq:
                    continue  # covered by the checkpoint
                self.store.write_row(int(rec["slot"]), rec["row"])
                self.seq = int(rec["seq"])
                self._tail_note(int(rec["slot"]))
                tail += 1
        if os.path.exists(ckpt) or tail:
            self.restored_from = (self._last_ckpt_seq, tail)
            logger.info(
                "replay shard %d restored: checkpoint seq %d + %d spill-"
                "tail rows -> seq %d", self.shard_id, self._last_ckpt_seq,
                tail, self.seq,
            )

    def checkpoint(self):
        """Atomic snapshot of the columns + seq cursor, then spill-log
        rotation (old spills are fully covered by the snapshot and
        deleted; a crash between the two steps is safe — restore skips
        spill records at or below the checkpoint seq)."""
        if self.data_dir is None:
            return None
        from blendjax.utils.checkpoint import save_state

        path = self._ckpt_path()
        save_state(
            path, dict(self.store.state_arrays()),
            {
                "format": SHARD_FORMAT,
                "shard_id": self.shard_id,
                "capacity": self.capacity,
                "seq": self.seq,
            },
        )
        self._last_ckpt_seq = self.seq
        if self._spill is not None:
            self._spill.__exit__(None, None, None)
        for old in self._spill_paths():
            try:
                os.unlink(old)
            except OSError:
                pass
        self._open_spill()
        return path

    # -- request handling ----------------------------------------------------

    def handle(self, msg):
        """Dispatch one decoded request dict -> reply dict (correlation
        id echoed; retried mutating requests served from the reply
        cache — exactly-once at the storage level).  A request carrying
        a span context (``wire.SPAN_KEY``) gets this shard's
        recv->storage->reply span piggybacked on the reply (a cached
        reply keeps the ORIGINAL simulation's span — the retry did no
        storage work)."""
        mid = msg.get(wire.BTMID_KEY)
        cmd = msg.get("cmd")
        if mid is not None and cmd in ("append", "save") \
                and mid in self._reply_cache:
            return self._reply_cache[mid]
        span_ctx = msg.get(wire.SPAN_KEY)
        t0_us = now_us() if isinstance(span_ctx, dict) else 0
        t0 = time.perf_counter()
        try:
            reply = getattr(self, f"_cmd_{cmd}", self._cmd_unknown)(msg)
        except Exception as exc:  # noqa: BLE001 - surfaced to the client
            logger.exception(
                "replay shard %d: %r failed", self.shard_id, cmd
            )
            reply = {"error": f"{type(exc).__name__}: {exc}"}
        # stage name clamped to DISPATCHED commands: the cmd string is
        # client-supplied, and one histogram per distinct garbage value
        # would grow timer memory (and scrape cardinality) unboundedly
        stage = (
            f"shard_srv_{cmd}"
            if hasattr(self, f"_cmd_{cmd}") else "shard_srv_unknown"
        )
        self.timer.add(stage, time.perf_counter() - t0, _t0=t0)
        if isinstance(span_ctx, dict) and span_ctx.get("trace") is not None:
            reply[wire.SPANS_KEY] = [make_span(
                f"shard{self.shard_id}:{cmd}", t0_us,
                trace=span_ctx["trace"], cat="replay_shard",
            )]
        if mid is not None:
            reply[wire.BTMID_KEY] = mid
            if cmd in ("append", "save"):
                self._reply_cache[mid] = reply
                while len(self._reply_cache) > wire.REPLY_CACHE_DEPTH:
                    self._reply_cache.popitem(last=False)
        return reply

    def _cmd_unknown(self, msg):
        raise ValueError(f"unknown replay shard command {msg.get('cmd')!r}")

    def _cmd_hello(self, msg):
        return {
            "shard_id": self.shard_id,
            "capacity": self.capacity,
            "seq": self.seq,
            "keys": list(self.store.keys),
            "restored_from": self.restored_from,
            # shm endpoint advertisement (None = pure-ZMQ shard); the
            # actual upgrade negotiation rides shm_connect/shm_attach
            "shm": self._shm.info() if self._shm is not None else None,
        }

    def _cmd_append(self, msg):
        slots = msg["slots"]
        rows = msg["rows"]
        if len(slots) != len(rows):
            raise ValueError(
                f"append: {len(slots)} slots vs {len(rows)} rows"
            )
        for slot, row in zip(slots, rows):
            self.store.write_row(int(slot), row)
            self.seq += 1
            self._tail_note(int(slot))
            if self._spill is not None:
                rec = {"slot": int(slot), "seq": self.seq, "row": row}
                if not self._spill.save(rec):
                    # spill at capacity: the ack below promises this row
                    # survives a crash, so roll a checkpoint (which
                    # rotates to a fresh spill) instead of dropping
                    self.checkpoint()
                    if not self._spill.save(rec):
                        raise RuntimeError(
                            f"shard {self.shard_id}: spill refused a "
                            "record even after rotation"
                        )
        if self._spill is not None:
            # durability point: the ack promises crash-exact recovery,
            # so the spill bytes must reach the OS before the reply does
            self._spill.flush()
        if self.checkpoint_every > 0 and \
                self.seq - self._last_ckpt_seq >= self.checkpoint_every:
            self.checkpoint()
        return {"seq": self.seq}

    def _cmd_gather(self, msg):
        indices = np.asarray(msg["indices"], np.int64)
        keys = msg.get("keys")
        out = self._gather_dst if self._reply_synchronous else None
        data = self.store.gather(indices, keys=keys, out=out)
        return {"data": data, "seq": self.seq}

    def _gather_dst(self, key, shape, dtype):
        """Recycled gather-reply buffers: fresh multi-MB batches pay
        page faults on every RPC that a reused destination never sees.
        Only offered on the shm reply path (``_reply_synchronous``):
        ``send_frames`` memcpys into the ring BEFORE returning, so the
        next request can never observe a half-overwritten buffer —
        whereas ZMQ's ``copy=False`` send keeps the frames referenced
        asynchronously."""
        buf = self._gather_bufs.get(key)
        if buf is None or buf.shape != shape or buf.dtype != dtype:
            buf = self._gather_bufs[key] = np.empty(shape, dtype)
        return buf

    def _cmd_stats(self, msg):
        return {
            "shard_id": self.shard_id,
            "capacity": self.capacity,
            "seq": self.seq,
            "nbytes": self.store.nbytes,
            "keys": list(self.store.keys),
            "last_checkpoint_seq": self._last_ckpt_seq,
            "spill_dropped": (
                self._spill.dropped if self._spill is not None else 0
            ),
        }

    def _cmd_save(self, msg):
        path = self.checkpoint()
        return {"path": path, "seq": self.seq}

    def _tail_note(self, slot):
        self._tail_slots.append((self.seq, slot))
        if len(self._tail_slots) > TAIL_SLOTS_CAP:
            evicted_seq, _ = self._tail_slots.popleft()
            self._tail_floor = evicted_seq

    def _cmd_written_since(self, msg):
        """Slots this shard wrote after durability cursor ``seq`` —
        the learner-failover reconcile query (a client restored from a
        checkpoint cut at ``seq`` invalidates exactly these slots: they
        hold rows its rewound draw state does not describe, and the
        resumed appends will rewrite them in the same ring order).
        The tail survives checkpoints — a cut can legitimately predate
        the shard's LATEST checkpoint when the learner died between a
        barrier's shard save and its manifest commit.
        ``complete=False`` when the tail cannot answer exactly (the cut
        predates the bounded mirror's floor: eviction, or a process
        restart whose pre-checkpoint appends are unknowable) — the
        caller rolls the whole range back instead of trusting a
        partial list."""
        since = int(msg["seq"])
        complete = since >= self._tail_floor
        slots = sorted({
            slot for q, slot in self._tail_slots if q > since
        }) if complete else []
        return {
            "seq": self.seq,
            "complete": bool(complete),
            "slots": slots,
        }

    def _cmd_telemetry(self, msg):
        """This process's telemetry in the TelemetryHub merge shape:
        counters + per-stage latency histograms (serialized sparse).
        The PULL half of cross-process scraping — a consumer-side hub
        registers ``lambda: client.rpc("telemetry")`` as a remote and
        this shard needs no exporter, no extra socket, no jax."""
        return {
            "shard_id": self.shard_id,
            "pid": os.getpid(),
            "seq": self.seq,
            "counters": self.counters.snapshot(),
            "stages": self.timer.snapshot_serialized(),
        }

    # -- serving -------------------------------------------------------------

    def _handle_shm(self, chan, msg):
        """One shm-delivered request: same dispatch, reply down the
        same channel (span piggybacks, reply cache, correlation ids —
        all transport-blind inside :meth:`handle`).  The synchronous
        reply write unlocks the recycled gather buffers, and ``gather``
        replies take the zero-copy fast path when they can."""
        if msg.get("cmd") == "gather" and wire.SPAN_KEY not in msg \
                and self._gather_into_ring(chan, msg):
            return
        self._reply_synchronous = True
        try:
            reply = self.handle(msg)
            self._shm.send(chan, reply, raw_buffers=True)
        finally:
            self._reply_synchronous = False

    def _gather_into_ring(self, chan, msg):
        """Zero-copy gather reply: the columnar batch is gathered
        DIRECTLY into the reply ring's record (``begin_send`` views)
        instead of staged through temp arrays and memcpy'd by
        ``send_frames`` — one copy total on the server, store ->
        shared memory.  Returns False to defer to the generic path
        (untraced requests only; malformed requests go generic so they
        get their proper error replies)."""
        from blendjax.native.ring import gather_into

        cols = self.store.columns
        try:
            idx = np.asarray(msg["indices"], np.int64)
        except (KeyError, TypeError, ValueError):
            return False
        keys = msg.get("keys") or list(cols)
        n = int(idx.size)
        if any(k not in cols for k in keys) or (
            n and (idx.min() < 0 or idx.max() >= self.capacity)
        ):
            return False
        t0 = time.perf_counter()
        header = {"data": {}, "seq": self.seq}
        mid = msg.get(wire.BTMID_KEY)
        if mid is not None:
            header[wire.BTMID_KEY] = mid
        sizes = [0]
        specs = []
        for i, key in enumerate(keys):
            col = cols[key]
            row_shape = col.shape[1:]
            row_bytes = col[0].nbytes if row_shape else col.itemsize
            header["data"][key] = {
                wire.ARRAY_PLACEHOLDER: i,
                "dtype": col.dtype.str,
                "shape": (n,) + tuple(int(d) for d in row_shape),
            }
            sizes.append(n * int(row_bytes))
            specs.append((col, bool(row_shape) and row_bytes >= 1024))
        head_bytes = wire.dumps(header)
        sizes[0] = len(head_bytes)
        views = self._shm.begin_send(chan, sizes)
        if views is None:
            return False
        done = False
        try:
            views[0][:] = np.frombuffer(head_bytes, np.uint8)
            for (col, native), dst in zip(specs, views[1:]):
                if native:
                    gather_into(dst, [col[i] for i in idx])
                elif n:
                    tmp = np.ascontiguousarray(np.take(col, idx, axis=0))
                    dst[:] = tmp.view(np.uint8).reshape(-1)
            done = True
        finally:
            if not done:
                # a torn record with an intact header would decode as
                # WRONG data — poison the header so the client drops
                # the record (and its retry re-gathers), then publish:
                # the reservation must never dangle
                views[0][: min(8, len(head_bytes))] = 0
            self._shm.commit_send(chan)
        self.timer.add("shard_srv_gather", time.perf_counter() - t0,
                       _t0=t0)
        return True

    def serve_forever(self, stop_event=None, poll_ms=100):
        """Serve loop until ``stop_event`` (or :meth:`close`): the REP
        socket (one request == one reply; raw-buffer replies keep image
        gathers off the pickle path) and, when ShmRPC is up, every
        attached shm channel — the transport's doorbell fd parks in the
        same poller, so shm requests wake the loop as promptly as ZMQ
        ones."""
        import zmq

        poller = zmq.Poller()
        poller.register(self._sock, zmq.POLLIN)
        if self._shm is not None and self._shm.fd is not None:
            poller.register(self._shm.fd, zmq.POLLIN)
        while stop_event is None or not stop_event.is_set():
            try:
                events = dict(poller.poll(poll_ms))
            except zmq.ZMQError:
                return  # socket closed under us: clean shutdown
            if self._shm is not None:
                self._shm.pump(self._handle_shm)
            if self._sock not in events:
                continue
            try:
                msg, nbytes = wire.recv_message_sized(self._sock)
            except zmq.ZMQError:
                return
            self.counters.incr("replay_wire_bytes", nbytes)
            # shm control commands are transport negotiation, not
            # storage workload: answered outside handle() (no reply
            # cache, no stage timer, no request counters)
            reply = shm_rpc.control_reply(self._shm, msg)
            if reply is None:
                reply = self.handle(msg)
            try:
                sent = wire.send_message(self._sock, reply,
                                         raw_buffers=True)
                self.counters.incr("replay_wire_bytes", sent)
            except zmq.ZMQError:
                return

    def close(self):
        try:
            self._sock.close(0)
        except Exception:  # noqa: BLE001 - shutdown best-effort
            pass
        if self._shm is not None:
            try:
                self._shm.close(unlink=True)
            except Exception:  # noqa: BLE001
                pass
            self._shm = None
        if self._spill is not None:
            try:
                self._spill.__exit__(None, None, None)
            except Exception:  # noqa: BLE001
                pass
            self._spill = None


class _LocalShardHandle:
    """An in-process shard server (thread) for tests and benchmarks."""

    def __init__(self, shard, thread, stop):
        self.shard = shard
        self.address = shard.address
        self._thread = thread
        self._stop = stop

    def close(self):
        self._stop.set()
        self._thread.join(timeout=5)
        self.shard.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def start_shard_thread(capacity, *, shard_id=0, data_dir=None,
                       checkpoint_every=0, address="tcp://127.0.0.1:*",
                       counters=None):
    """Serve a :class:`ReplayShard` from a daemon thread; returns a
    handle with ``.address`` and ``.close()``.  Same wire surface as a
    shard process — the benchmark's service windows and most service
    tests run on these."""
    shard = ReplayShard(
        address, capacity, shard_id=shard_id, data_dir=data_dir,
        checkpoint_every=checkpoint_every, counters=counters,
    )
    stop = threading.Event()
    thread = threading.Thread(
        target=shard.serve_forever, kwargs={"stop_event": stop},
        daemon=True, name=f"bjx-replay-shard-{shard_id}",
    )
    thread.start()
    return _LocalShardHandle(shard, thread, stop)


class _ShardLaunchInfo:
    """Duck-typed ``launch_info`` so :class:`~blendjax.btt.watchdog.
    FleetWatchdog` / :class:`~blendjax.btt.supervise.FleetSupervisor`
    supervise shard processes exactly like Blender producers.  The
    shards' ``shm://`` endpoints ride along under ``REPLAY_SHM`` (empty
    when ShmRPC is disabled) — the launch-info half of the transport
    advertisement; clients negotiate the actual upgrade in-band."""

    def __init__(self, processes, addresses, shm_addresses=()):
        self.processes = processes
        self.addresses = {"REPLAY": addresses,
                          "REPLAY_SHM": list(shm_addresses)}


class ShardFleet:
    """N replay shard *processes* with a launcher-compatible surface.

    Each shard binds ``tcp://127.0.0.1:<port_i>``, persists under
    ``data_dir`` and is spawned in its own session (so
    :func:`blendjax.btt.chaos.kill_instance` kills the shard, not the
    test).  ``respawn(idx)`` relaunches the same command line — the
    restarted process restores its checkpoint + spill tail on its own —
    which is what ``FleetSupervisor(restart=True)`` calls after a death.

    Usage::

        with ShardFleet(3, capacity_per_shard=4096, data_dir=d) as fleet:
            sharded = ShardedReplay(fleet.addresses, seed=0)
            sup = FleetSupervisor(fleet, pool=None, replay=sharded,
                                  counters=sharded.counters)
    """

    def __init__(self, num_shards, capacity_per_shard, data_dir, *,
                 checkpoint_every=1024, python=None, ready_timeout=30.0):
        if num_shards < 1 or capacity_per_shard < 1:
            raise ValueError(
                "num_shards and capacity_per_shard must be >= 1"
            )
        self.num_shards = int(num_shards)
        self.capacity_per_shard = int(capacity_per_shard)
        self.data_dir = data_dir
        self.checkpoint_every = int(checkpoint_every)
        self.python = python or sys.executable
        self.ready_timeout = ready_timeout
        self.addresses = []
        self.launch_info = None
        self._cmds = []
        #: per-shard /dev/shm prefixes, allocated HERE (the parent) so
        #: teardown and the watchdog respawn path can sweep the objects
        #: a SIGKILLed shard (and its clients) left behind
        self.shm_bases = [
            shm_rpc.new_base(f"sf{i}") if shm_rpc.enabled() else None
            for i in range(self.num_shards)
        ]

    def _spawn(self, cmd):
        # shared child-environment policy (see launcher.child_env:
        # repo root prepended to PYTHONPATH); function-level import so
        # the shard child's own fast-start surface stays lean
        from blendjax.btt.launcher import child_env

        return subprocess.Popen(cmd, env=child_env(),
                                start_new_session=True)

    def __enter__(self):
        from blendjax.replay.shard_client import free_port

        os.makedirs(self.data_dir, exist_ok=True)
        procs = []
        try:
            for i in range(self.num_shards):
                addr = f"tcp://127.0.0.1:{free_port()}"
                cmd = [
                    self.python, "-m", "blendjax.replay.service",
                    "--address", addr,
                    "--capacity", str(self.capacity_per_shard),
                    "--shard-id", str(i),
                    "--dir", str(self.data_dir),
                    "--checkpoint-every", str(self.checkpoint_every),
                ]
                if self.shm_bases[i] is not None:
                    cmd += ["--shm-base", self.shm_bases[i]]
                procs.append(self._spawn(cmd))
                self.addresses.append(addr)
                self._cmds.append(cmd)
            self.launch_info = _ShardLaunchInfo(
                procs, self.addresses, self._shm_addresses()
            )
            self.wait_ready(self.ready_timeout)
        except BaseException:
            self.launch_info = _ShardLaunchInfo(
                procs, self.addresses, self._shm_addresses()
            )
            self.close()
            raise
        return self

    def _shm_addresses(self):
        return [f"shm://{b}" for b in self.shm_bases if b is not None]

    def wait_ready(self, timeout=30.0):
        """Block until every shard answers ``hello`` — the deterministic
        startup barrier (counters measured after it reflect injected
        faults only, never shard boot time)."""
        from blendjax.replay.shard_client import ShardClient

        deadline = time.monotonic() + timeout
        for i, addr in enumerate(self.addresses):
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"replay shard {i} at {addr} not ready within "
                        f"{timeout:.1f}s"
                    )
                client = ShardClient(addr, i, timeoutms=500)
                try:
                    client.rpc("hello", timeout_ms=500)
                    break
                except TimeoutError:
                    continue
                finally:
                    client.close()

    def respawn(self, idx):
        """Relaunch shard ``idx`` with its original command line (the
        watchdog's contract).  The fresh process restores checkpoint +
        spill tail from ``data_dir`` before serving.  The dead
        incarnation's ``/dev/shm`` objects (rings, bells — a SIGKILL
        runs no cleanup) are swept FIRST, so generations cannot pile up
        across a chaos run's kill/respawn cycles."""
        if (self.launch_info is not None
                and self.launch_info.processes[idx] is None):
            raise RuntimeError(
                f"replay shard {idx} is retired; a retired slot is "
                "never respawned"
            )
        if self.shm_bases[idx] is not None:
            shm_rpc.unlink_base(self.shm_bases[idx])
        proc = self._spawn(self._cmds[idx])
        self.launch_info.processes[idx] = proc
        return proc

    def grow(self, restore_ckpt=None):
        """Spawn ONE additional shard process (the storage half of live
        replay resharding, docs/autoscaling.md).  With ``restore_ckpt``
        the new shard boots already holding a source shard's rows: the
        checkpoint file is copied under the new shard's own name before
        launch, so ``_restore_from_disk`` adopts it (the shard restore
        path validates format + capacity, not shard id — a handoff IS a
        copied checkpoint restoring elsewhere).  Without it any stale
        on-disk state for the new index is removed so the shard boots
        empty.  Blocks until the shard answers ``hello``; on failure
        the process is retired and the fleet is unchanged.  Returns
        ``(idx, address)``."""
        import shutil

        from blendjax.replay.shard_client import ShardClient, free_port

        if self.launch_info is None:
            raise RuntimeError("ShardFleet.grow before __enter__")
        idx = self.num_shards
        os.makedirs(self.data_dir, exist_ok=True)
        ckpt = os.path.join(self.data_dir, f"shard_{idx:02d}.ckpt.npz")
        for stale in glob.glob(os.path.join(
                self.data_dir, f"shard_{idx:02d}.spill-*.btr")):
            os.remove(stale)
        if restore_ckpt is not None:
            shutil.copyfile(restore_ckpt, ckpt)
        elif os.path.exists(ckpt):
            os.remove(ckpt)
        addr = f"tcp://127.0.0.1:{free_port()}"
        base = shm_rpc.new_base(f"sf{idx}") if shm_rpc.enabled() else None
        cmd = [
            self.python, "-m", "blendjax.replay.service",
            "--address", addr,
            "--capacity", str(self.capacity_per_shard),
            "--shard-id", str(idx),
            "--dir", str(self.data_dir),
            "--checkpoint-every", str(self.checkpoint_every),
        ]
        if base is not None:
            cmd += ["--shm-base", base]
        proc = self._spawn(cmd)
        self.shm_bases.append(base)
        self._cmds.append(cmd)
        self.num_shards = idx + 1
        self.addresses.append(addr)  # aliased by launch_info (REPLAY)
        self.launch_info.processes.append(proc)
        if base is not None:
            self.launch_info.addresses["REPLAY_SHM"].append(
                f"shm://{base}"
            )
        deadline = time.monotonic() + self.ready_timeout
        try:
            while True:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"grown replay shard {idx} at {addr} not ready "
                        f"within {self.ready_timeout:.1f}s"
                    )
                client = ShardClient(addr, idx, timeoutms=500)
                try:
                    client.rpc("hello", timeout_ms=500)
                    break
                except TimeoutError:
                    continue
                finally:
                    client.close()
        except BaseException:
            self.retire(idx)
            raise
        logger.info("replay shard %d grown at %s (restore_ckpt=%s)",
                    idx, addr, restore_ckpt)
        return idx, addr

    def retire(self, idx):
        """Stop shard ``idx`` and mark its slot retired (``None``): the
        watchdog skips it and :meth:`respawn` refuses it.  Sweeps its
        ``/dev/shm`` objects.  Idempotent; returns True when a live
        process was actually stopped."""
        procs = self.launch_info.processes if self.launch_info else []
        p = procs[idx] if 0 <= idx < len(procs) else None
        if p is not None:
            # slot goes None BEFORE the kill: a watchdog polling
            # between the two must see a retired slot, not a death
            procs[idx] = None
            try:
                p.terminate()
                p.wait(timeout=5)
            except Exception:  # noqa: BLE001
                try:
                    p.kill()
                    p.wait(timeout=5)
                except Exception:  # noqa: BLE001
                    pass
        if idx < len(self.shm_bases) and self.shm_bases[idx] is not None:
            shm_rpc.unlink_base(self.shm_bases[idx])
        if p is not None:
            logger.info("replay shard %d retired", idx)
        return p is not None

    def close(self):
        info = self.launch_info
        if info is None:
            return
        for p in info.processes:
            if p is None:
                continue
            try:
                p.terminate()
            except Exception:  # noqa: BLE001
                pass
        for p in info.processes:
            if p is None:
                continue
            try:
                p.wait(timeout=5)
            except Exception:  # noqa: BLE001
                try:
                    p.kill()
                except Exception:  # noqa: BLE001
                    pass
        # the processes are down: sweep every shm object of the fleet
        # (the registered-names half of the no-leaked-/dev/shm contract)
        for base in self.shm_bases:
            if base is not None:
                shm_rpc.unlink_base(base)

    def __exit__(self, *exc):
        self.close()
        return False


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Serve one blendjax replay storage shard."
    )
    ap.add_argument("--address", required=True,
                    help="endpoint to bind, e.g. tcp://127.0.0.1:23000")
    ap.add_argument("--capacity", type=int, required=True)
    ap.add_argument("--shard-id", type=int, default=0)
    ap.add_argument("--dir", default=None,
                    help="durability root (checkpoints + .btr spill)")
    ap.add_argument("--checkpoint-every", type=int, default=0)
    ap.add_argument("--shm-base", default=None,
                    help="/dev/shm name prefix for the ShmRPC transport "
                         "(supervising parents pass one so they can "
                         "sweep a SIGKILLed shard's objects)")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    shard = ReplayShard(
        args.address, args.capacity, shard_id=args.shard_id,
        data_dir=args.dir, checkpoint_every=args.checkpoint_every,
        shm_base=args.shm_base,
    )
    stop = threading.Event()

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    logger.info(
        "replay shard %d serving %s (capacity %d, dir %s)",
        args.shard_id, shard.address, args.capacity, args.dir,
    )
    try:
        shard.serve_forever(stop_event=stop)
    finally:
        shard.close()


if __name__ == "__main__":
    main()
