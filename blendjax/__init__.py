"""blendjax — TPU-native real-time Blender -> JAX streaming framework.

A ground-up, TPU-first re-design of the capabilities of blendtorch
(reference: ``/root/reference``, see SURVEY.md): N Blender processes render
randomized scenes and stream images + annotations over ZMQ into a JAX/XLA
training pipeline, with bi-directional control channels and gym-style remote
environments.  The consumer side replaces torch DataLoaders with a threaded
stream loader feeding a double-buffered ``jax.device_put`` prefetcher so
frames land directly in TPU HBM; scale-out is per-host Blender fleets plus
``jax.sharding`` meshes on the training side.

Subpackages
-----------
- ``blendjax.btt``   consumer side (host / JAX): launcher, streaming dataset,
  record/replay, duplex channel, remote environments, device feed.
- ``blendjax.btb``   producer side (runs inside Blender's Python): animation
  controller, offscreen renderer, camera annotations, publisher, duplex,
  remote-controlled environments.  Importable without bpy/jax installed.
- ``blendjax.models``  TPU-first example models (detector, discriminator,
  probability model, policies) in pure jax + optax.
- ``blendjax.ops``     image ops (sRGB decode, normalize, augment) incl. a
  Pallas TPU kernel for the hot uint8->bf16 path.
- ``blendjax.parallel`` mesh/sharding helpers and the vectorized env pool.
- ``blendjax.serve``   policy-serving inference tier: continuous batching
  of ``step()`` over the DEALER wire, KV-cache slot pools, int8 serving.
- ``blendjax.obs``     unified telemetry plane: latency histograms,
  cross-process trace spans, TelemetryHub scrapes, flight recorders.
- ``blendjax.scenario`` scenario plane: named scene catalogs, live
  domain randomization over the duplex control plane, curriculum
  scheduling of the fleet's scenario mix.
- ``blendjax.utils``    timing/tracing, logging.

This module is import-light on purpose: importing :mod:`blendjax` pulls in
neither jax, torch, nor bpy, so the same wheel serves Blender's embedded
Python and the TPU host.
"""

__version__ = "0.1.0"

from blendjax import wire  # noqa: F401  (pure stdlib + zmq/numpy, always safe)

_SUBMODULES = (
    "btt", "btb", "models", "obs", "ops", "parallel", "scenario",
    "utils", "wire",
)


def __getattr__(name):  # PEP 562 lazy subpackage access
    if name in _SUBMODULES:
        import importlib

        mod = importlib.import_module(f"blendjax.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'blendjax' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_SUBMODULES))
