"""Wire protocol shared by producer (Blender) and consumer (JAX host).

The reference spreads its wire format across both packages (pickled dict via
``send_pyobj``/``recv_pyobj`` with an auto-stamped producer id — reference
``pkg_blender/blendtorch/btb/publisher.py:41-43``,
``pkg_pytorch/blendtorch/btt/dataset.py:105``,
``*/duplex.py:60-66``).  blendjax centralizes it here and keeps two
interoperable encodings on every socket:

1. **compat** — one frame holding ``pickle.dumps(dict)``.  Byte-compatible
   with reference producers/consumers, so existing ``*.blend.py`` publisher
   scripts stream into blendjax unmodified and vice versa.
2. **raw-buffer** — multipart ``[header, buf0, buf1, ...]`` where the header
   is a pickled dict with ndarray leaves replaced by placeholders and the
   array payloads ride as separate zero-copy ZMQ frames.  Decoding is a
   ``np.frombuffer`` view per array instead of a pickle memcpy — the biggest
   serialization win for 640x480x4 frames (SURVEY.md §7 "hard parts").

Receivers auto-detect the encoding per message (multipart => raw-buffer), so
mixed fleets work.

Pickle protocol is pinned to 4: the newest protocol that Blender 2.8x's
bundled Python 3.7 can read (the reference pins protocol 3 for the same
reason in ``pkg_pytorch/blendtorch/btt/file.py:59-63``; 4 is available from
Python 3.4 and is faster for large buffers).
"""

from __future__ import annotations

import os
import pickle
import random as _random

import numpy as np
import zmq

#: Newest pickle protocol readable by every Blender >= 2.80 (Python >= 3.7).
PICKLE_PROTOCOL = 4

#: Default high-water mark on both ends of the data plane.  Small on purpose:
#: a slow trainer stalls producers (backpressure) instead of buffering
#: unboundedly (reference ``publisher.py:24-27``, ``dataset.py:73-78``).
DEFAULT_HWM = 10

#: Key stamped into every data-plane message identifying the producer
#: instance (reference ``publisher.py:42``).
BTID_KEY = "btid"

#: Key stamped into every duplex message: a random per-message id usable for
#: request/response correlation (reference ``duplex.py:60-66``).
BTMID_KEY = "btmid"

#: Key under which a tracing client stamps its span context into a
#: request (``{"trace": <correlation id>}``): a server that sees it
#: records its own recv->work->reply span and ships it back under
#: :data:`SPANS_KEY`.  Servers that ignore the key keep working
#: (third-party/legacy producers simply contribute no server-side
#: spans); see :mod:`blendjax.obs.spans`.
SPAN_KEY = "btspan"

#: Key under which a server piggybacks its recorded spans (a list of
#: chrome-tracing event dicts) on a reply.  Clients POP it before the
#: reply becomes user-visible data (infos, replay rows), whether or not
#: they are tracing.
SPANS_KEY = "btspans"

_ARRAY_PLACEHOLDER = "__bjx_nd__"

#: Public alias: key under which a raw-buffer header stores the payload
#: frame index for an ndarray leaf (consumed by the batched shm decode).
ARRAY_PLACEHOLDER = _ARRAY_PLACEHOLDER


def is_array_placeholder(obj) -> bool:
    """True if ``obj`` is a raw-buffer header placeholder for an ndarray."""
    return isinstance(obj, dict) and _ARRAY_PLACEHOLDER in obj


#: producer-side duplicate-suppression window, in replies: a retried
#: request (same :data:`BTMID_KEY`) is answered from the producer's
#: reply cache only while its reply is among the newest
#: ``REPLY_CACHE_DEPTH`` served.  A protocol constant, not a tunable —
#: the consumer's ``pipeline_depth`` must stay within it or a retry of
#: the oldest in-flight request could re-simulate a frame.
REPLY_CACHE_DEPTH = 8

#: process-local generator seeded once from the OS: a per-message
#: ``os.urandom`` costs ~100 us under syscall-intercepting sandboxes,
#: which the pipelined EnvPool would pay per request — ``getrandbits``
#: is pure user-space after the seed
_MID_RNG = _random.Random(os.urandom(16))


def new_message_id() -> str:
    """Random 8-byte hex message id, drawn syscall-free from a
    process-local OS-seeded generator.  The reference's 4 bytes
    (``duplex.py:63``) sufficed for stale-reply detection, but the ids
    now key the producer's exactly-once reply cache: a fresh id
    colliding with one of the :data:`REPLY_CACHE_DEPTH` cached ids
    would silently serve a stale transition, so the width keeps that
    chance negligible over multi-day kHz-rate runs."""
    return f"{_MID_RNG.getrandbits(64):016x}"


def dumps(obj) -> bytes:
    return pickle.dumps(obj, protocol=PICKLE_PROTOCOL)


def loads(buf) -> object:
    return pickle.loads(buf)


# ---------------------------------------------------------------------------
# raw-buffer encoding
# ---------------------------------------------------------------------------


def _strip_arrays(obj, bufs: list):
    """Replace ndarray leaves in a nested container with placeholders.

    Supports the containers the data plane actually carries (dict/list/tuple
    of numpy arrays and scalars).  Non-contiguous arrays are copied once.
    """
    if isinstance(obj, np.ndarray):
        arr = np.ascontiguousarray(obj)
        bufs.append(arr)
        return {
            _ARRAY_PLACEHOLDER: len(bufs) - 1,
            "dtype": arr.dtype.str,
            # the ORIGINAL shape: ascontiguousarray promotes 0-d arrays
            # to (1,), which would silently grow a rank on the receiver
            # (a replay shard rejects the row as schema drift)
            "shape": obj.shape,
        }
    if isinstance(obj, dict):
        return {k: _strip_arrays(v, bufs) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        seq = [_strip_arrays(v, bufs) for v in obj]
        return seq if isinstance(obj, list) else tuple(seq)
    return obj


def _restore_arrays(obj, frames):
    if isinstance(obj, dict):
        if _ARRAY_PLACEHOLDER in obj:
            idx = obj[_ARRAY_PLACEHOLDER]
            arr = np.frombuffer(frames[idx], dtype=np.dtype(obj["dtype"]))
            return arr.reshape(obj["shape"])
        return {k: _restore_arrays(v, frames) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        seq = [_restore_arrays(v, frames) for v in obj]
        return seq if isinstance(obj, list) else tuple(seq)
    return obj


def strip_arrays(data: dict, bufs: list) -> dict:
    """Public half of the raw-buffer encoding: replace ndarray leaves
    with placeholder headers, appending each (contiguous) array to
    ``bufs``.  Gather-into-ring senders use it to learn a reply's frame
    layout BEFORE reserving the ring record, then land each array in
    its reserved view instead of staging through :func:`encode`."""
    return _strip_arrays(data, bufs)


def encode(data: dict, raw_buffers: bool = False) -> list:
    """Encode a message dict into a list of ZMQ frames."""
    if not raw_buffers:
        return [dumps(data)]
    bufs: list = []
    header = _strip_arrays(data, bufs)
    return [dumps(header)] + bufs


def decode(frames) -> dict:
    """Decode frames produced by :func:`encode` (either encoding)."""
    head = pickle.loads(frames[0])
    if len(frames) == 1:
        return head
    return _restore_arrays(head, [memoryview(f) for f in frames[1:]])


# ---------------------------------------------------------------------------
# socket send/recv
# ---------------------------------------------------------------------------


def frames_nbytes(frames) -> int:
    """Total payload bytes of a frame list — the transport-neutral
    wire-bytes unit behind the ``*_wire_bytes``/``*_shm_bytes``
    counters (what :func:`encode` produced, not what any particular
    wire wrapped around it)."""
    total = 0
    for f in frames:
        total += f.nbytes if hasattr(f, "nbytes") else len(f)
    return total


def send_message(socket: zmq.Socket, data: dict, raw_buffers: bool = False, flags: int = 0):
    """Send one message; returns the payload byte count (the senders'
    half of per-request wire-bytes accounting)."""
    frames = encode(data, raw_buffers=raw_buffers)
    if len(frames) == 1:
        socket.send(frames[0], flags=flags)
    else:
        socket.send_multipart(frames, flags=flags, copy=False)
    return frames_nbytes(frames)


def recv_message(socket: zmq.Socket, flags: int = 0) -> dict:
    return recv_message_sized(socket, flags=flags)[0]


def recv_message_sized(socket: zmq.Socket, flags: int = 0):
    """:func:`recv_message` plus the payload byte count — the receive
    half of per-request wire-bytes accounting (and the ONE copy of the
    receive/decode logic; the unsized form delegates here)."""
    frames = socket.recv_multipart(flags=flags, copy=False)
    bufs = [f.buffer for f in frames]
    return decode(bufs), frames_nbytes(bufs)


def stamp_message_id(data: dict) -> str:
    """Stamp ``data`` with a fresh correlation id under :data:`BTMID_KEY`
    and return it.  The async env pipeline uses this to match replies to
    in-flight requests (and the producer-side agent to dedupe re-sent
    ``step`` requests); receivers that ignore the key keep working."""
    mid = new_message_id()
    data[BTMID_KEY] = mid
    return mid


def stamp_span_context(data: dict, trace: str) -> None:
    """Stamp a request with the span context that asks the server for a
    piggybacked span (see :data:`SPAN_KEY`).  ``trace`` is the trace id
    the server's span will be tagged with — by convention the request's
    :data:`BTMID_KEY` correlation id, so client and server spans of one
    RPC share it."""
    data[SPAN_KEY] = {"trace": trace}


def pop_spans(reply: dict):
    """Remove and return a reply's piggybacked span list (None when the
    server attached none).  Reply consumers call this unconditionally so
    span payloads never leak into infos/rows."""
    return reply.pop(SPANS_KEY, None)


# ---------------------------------------------------------------------------
# DEALER <-> REP framing
# ---------------------------------------------------------------------------
#
# A DEALER socket talking to a REP peer must emulate the REQ envelope: an
# empty delimiter frame ahead of the message body.  The REP socket strips
# it on the way in and restores it on the way out, so existing REP-socket
# producers (``blendjax.btb.env.RemoteControlledAgent``) serve DEALER
# clients unmodified.  Unlike REQ, a DEALER has no strict send/recv
# alternation — which is exactly what the pipelined EnvPool needs to keep
# several requests in flight per env.


def send_message_dealer(socket: zmq.Socket, data: dict,
                        raw_buffers: bool = False, flags: int = 0):
    """Send ``data`` from a DEALER socket to a REP peer (empty-delimiter
    framing).  RPC control messages are small, so ``copy=True`` skips
    pyzmq's zero-copy Frame bookkeeping (measurably cheaper per message);
    bulk ndarray traffic belongs on the raw-buffer data plane, not here."""
    frames = encode(data, raw_buffers=raw_buffers)
    socket.send_multipart([b""] + frames, flags=flags,
                          copy=not raw_buffers)


def recv_message_dealer(socket: zmq.Socket, flags: int = 0) -> dict:
    """Receive a REP peer's reply on a DEALER socket, stripping the
    empty delimiter frame the REP socket re-attached."""
    bufs = socket.recv_multipart(flags=flags, copy=True)
    if bufs and len(bufs[0]) == 0:
        bufs = bufs[1:]
    return decode(bufs)


def recv_message_router(socket: zmq.Socket, flags: int = 0):
    """Receive one DEALER client's request on a ROUTER socket: returns
    ``(identity, message)`` where ``identity`` is the routing frame to
    hand back to :func:`send_message_router`.  Strips the empty
    delimiter :func:`send_message_dealer` framed with, so the same
    clients speak to REP servers and ROUTER servers unmodified — the
    many-clients half of the serving tier's continuous batching
    (``blendjax/serve``)."""
    ident, msg, _ = recv_message_router_sized(socket, flags=flags)
    return ident, msg


def recv_message_router_sized(socket: zmq.Socket, flags: int = 0):
    """:func:`recv_message_router` plus the payload byte count (and the
    ONE copy of the delimiter-strip logic; the unsized form delegates
    here)."""
    frames = socket.recv_multipart(flags=flags, copy=True)
    ident, body = frames[0], frames[1:]
    if body and len(body[0]) == 0:
        body = body[1:]
    return ident, decode(body), frames_nbytes(body)


def send_message_router(socket: zmq.Socket, ident: bytes, data: dict,
                        raw_buffers: bool = False, flags: int = 0):
    """Send ``data`` to the DEALER client behind routing frame
    ``ident``, restoring the empty delimiter the client's
    :func:`recv_message_dealer` strips.  Returns the payload byte
    count."""
    frames = encode(data, raw_buffers=raw_buffers)
    socket.send_multipart([ident, b""] + frames, flags=flags,
                          copy=False)
    return frames_nbytes(frames)


def recv_message_raw(socket: zmq.Socket, flags: int = 0):
    """Receive without decoding; returns the raw frame list (bytes).

    Used by the stream recorder, which persists the on-wire bytes verbatim
    (reference ``dataset.py:100-105`` records pre-unpickle bytes).
    """
    return socket.recv_multipart(flags=flags, copy=True)


def decode_raw_frames(frames) -> dict:
    """Decode frames previously captured by :func:`recv_message_raw`."""
    return decode(frames)
