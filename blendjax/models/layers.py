"""Minimal functional NN layers (pure jax pytrees).

blendjax models are plain ``{name: array}`` pytrees with ``init``/``apply``
functions — no module framework — so they jit, shard (NamedSharding over
pytree leaves), and donate cleanly.  Convs are NHWC/HWIO, the TPU-native
layout; compute dtype is a parameter so models run bfloat16 on the MXU with
float32 params.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def conv_init(key, in_ch, out_ch, ksize=3):
    """He-normal conv kernel (HWIO) + zero bias."""
    fan_in = ksize * ksize * in_ch
    w = jax.random.normal(key, (ksize, ksize, in_ch, out_ch)) * jnp.sqrt(2.0 / fan_in)
    return {"w": w, "b": jnp.zeros((out_ch,))}


def conv_apply(p, x, stride=1, padding="SAME", dtype=None):
    dtype = dtype or x.dtype
    out = lax.conv_general_dilated(
        x.astype(dtype),
        p["w"].astype(dtype),
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return out + p["b"].astype(dtype)


def dense_init(key, d_in, d_out):
    w = jax.random.normal(key, (d_in, d_out)) * jnp.sqrt(2.0 / d_in)
    return {"w": w, "b": jnp.zeros((d_out,))}


def dense_apply(p, x, dtype=None):
    dtype = dtype or x.dtype
    return x.astype(dtype) @ p["w"].astype(dtype) + p["b"].astype(dtype)


def gelu(x):
    return jax.nn.gelu(x)


def rope_table(positions, dh, base=10000.0):
    """Rotary-embedding cos/sin tables for ``positions`` (any traced or
    static int array) at per-head dim ``dh`` (even).  f32: the rotation
    is applied in f32 and cast back by :func:`apply_rope`.

    Precision bound: the highest-frequency angle equals the raw
    position, and f32's ulp at position p is ~p * 6e-8 radians — sub-
    milliradian phase error through ~1e4, ~1e-2 rad at 1e5-1e6, and
    meaningless past 2^24 (adjacent positions collide).  Practical
    horizon ~1e5-1e6 positions; a reduced-angle scheme would be needed
    beyond that."""
    half = dh // 2
    freqs = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = positions.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x, cos, sin):
    """Rotate (B, T, H, Dh) (or (B, H, Dh) single-position) q/k by the
    tables from :func:`rope_table`.  Rotation by absolute position makes
    q·k depend only on the RELATIVE offset — the property that unties
    sequence length from any learned table."""
    single = x.ndim == 3
    if single:
        x = x[:, None]
    x32 = x.astype(jnp.float32)
    x1, x2 = jnp.split(x32, 2, axis=-1)
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    out = jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
    out = out.astype(x.dtype)
    return out[:, 0] if single else out


def apply_rope_rows(x, cos, sin):
    """Rotate a single-position (B, H, Dh) q/k where each batch row sits
    at its OWN position: ``cos``/``sin`` are (B, Dh/2) tables from
    :func:`rope_table` over a (B,) position vector.  The per-row decode
    path of :func:`blendjax.models.seqformer.decode_step` (policy
    serving: one batched step over episodes at heterogeneous timesteps)
    uses this; :func:`apply_rope` covers the batch-uniform case."""
    x32 = x.astype(jnp.float32)
    x1, x2 = jnp.split(x32, 2, axis=-1)
    c = cos[:, None, :]
    s = sin[:, None, :]
    out = jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
    return out.astype(x.dtype)
