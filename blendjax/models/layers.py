"""Minimal functional NN layers (pure jax pytrees).

blendjax models are plain ``{name: array}`` pytrees with ``init``/``apply``
functions — no module framework — so they jit, shard (NamedSharding over
pytree leaves), and donate cleanly.  Convs are NHWC/HWIO, the TPU-native
layout; compute dtype is a parameter so models run bfloat16 on the MXU with
float32 params.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def conv_init(key, in_ch, out_ch, ksize=3):
    """He-normal conv kernel (HWIO) + zero bias."""
    fan_in = ksize * ksize * in_ch
    w = jax.random.normal(key, (ksize, ksize, in_ch, out_ch)) * jnp.sqrt(2.0 / fan_in)
    return {"w": w, "b": jnp.zeros((out_ch,))}


def conv_apply(p, x, stride=1, padding="SAME", dtype=None):
    dtype = dtype or x.dtype
    out = lax.conv_general_dilated(
        x.astype(dtype),
        p["w"].astype(dtype),
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return out + p["b"].astype(dtype)


def dense_init(key, d_in, d_out):
    w = jax.random.normal(key, (d_in, d_out)) * jnp.sqrt(2.0 / d_in)
    return {"w": w, "b": jnp.zeros((d_out,))}


def dense_apply(p, x, dtype=None):
    dtype = dtype or x.dtype
    return x.astype(dtype) @ p["w"].astype(dtype) + p["b"].astype(dtype)


def gelu(x):
    return jax.nn.gelu(x)
