"""Log-normal probability model over simulation parameters + score-function
(REINFORCE) gradients — the densityopt simulator-side model.

Counterpart of the reference's torch ``ProbModel``
(``examples/densityopt/densityopt.py:30-93``): a distribution over
supershape parameters (m1, m2) whose samples are pushed through a
**non-differentiable renderer** (Blender).  Gradients flow via the
likelihood-ratio trick with an EMA baseline
(``densityopt.py:278-309``), never through the renderer:

    grad = E[ grad log p(sample) * (loss(sample) - baseline) ]

All estimator math is jittable; only the render round-trip (duplex send /
stream recv) stays host-side.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init(mu, sigma):
    """Params for independent log-normals: ``log X ~ N(mu, sigma)``.

    ``mu``/``sigma`` are length-K arrays (K simulation parameters).
    ``sigma`` is stored in log space for unconstrained optimization.
    """
    mu = jnp.asarray(mu, jnp.float32)
    return {"mu": mu, "log_sigma": jnp.log(jnp.asarray(sigma, jnp.float32))}


def sample(params, key, n):
    """(n, K) positive samples, reparameterized draw (but treated as
    non-differentiable by the estimator — matches the score-function
    setting where the renderer breaks the chain anyway)."""
    eps = jax.random.normal(key, (n, params["mu"].shape[-1]))
    return jnp.exp(params["mu"] + jnp.exp(params["log_sigma"]) * eps)


def log_prob(params, x):
    """Elementwise-summed log density of the log-normal at ``x`` (n, K)."""
    mu, sigma = params["mu"], jnp.exp(params["log_sigma"])
    z = (jnp.log(x) - mu) / sigma
    log_pdf = -0.5 * z * z - jnp.log(sigma) - 0.5 * jnp.log(2 * jnp.pi) - jnp.log(x)
    return log_pdf.sum(-1)


def score_loss(params, samples, losses, baseline):
    """Surrogate whose gradient is the score-function estimator.

    ``samples`` (n, K) came from ``sample``; ``losses`` (n,) were measured
    through the non-differentiable pipeline; ``baseline`` is a variance-
    reduction scalar (e.g. EMA of recent losses).
    """
    advantage = jax.lax.stop_gradient(losses - baseline)
    return jnp.mean(log_prob(params, jax.lax.stop_gradient(samples)) * advantage)


def ema_update(baseline, losses, decay=0.9):
    """EMA baseline update (reference keeps a running mean,
    ``densityopt.py:290-309``)."""
    return decay * baseline + (1.0 - decay) * losses.mean()


def mean(params):
    """Distribution mean of the log-normal: exp(mu + sigma^2/2)."""
    sigma = jnp.exp(params["log_sigma"])
    return jnp.exp(params["mu"] + 0.5 * sigma * sigma)
