"""Training-step builders: loss -> jitted, donated, optionally sharded step.

The reference has no training infrastructure (its examples hand-roll torch
loops); blendjax standardizes one functional pattern::

    state = TrainState.create(params, optax.adam(1e-3))
    step = make_train_step(loss_fn)
    state, loss = step(state, batch)          # jitted, state donated

and a mesh-sharded variant (see
:func:`blendjax.parallel.sharding.make_sharded_train_step`) where XLA
inserts the gradient all-reduce over the ``'data'`` axis and tensor-
parallel collectives over ``'model'`` from the sharding annotations alone.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import optax


class TrainState(NamedTuple):
    """Functional train state (params + optimizer state + step count)."""

    params: Any
    opt_state: Any
    step: Any

    @classmethod
    def create(cls, params, optimizer):
        return cls(params=params, opt_state=optimizer.init(params), step=0)


def make_train_step(loss_fn, optimizer=None, donate=True):
    """Build ``step(state, batch) -> (state, loss)``.

    ``loss_fn(params, batch) -> scalar``.  The state is donated so params
    update in place in HBM (no double-buffered weights).
    """
    optimizer = optimizer or optax.adam(1e-3)

    def step(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_eval_step(loss_fn):
    return jax.jit(loss_fn)
