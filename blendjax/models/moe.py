"""Routed top-k mixture-of-experts (static-shaped, expert-parallel).

The SeqFormer's original MoE is a *dense* soft mixture: every expert runs
on every token and the gate weights the sum (``seqformer._moe_apply``) —
expert **sharding**, but compute scales with ``n_experts`` regardless of
sparsity (VERDICT r01 weak #7).  This module adds true routed expert
parallelism the TPU way: top-k gating with a fixed per-expert **capacity**
so every shape is static under ``jit``, scatter/gather dispatch into a
per-expert slot arena (O(k*n*d) data movement; XLA lowers the arena
scatter to dynamic-update-slices, and sharding the expert axis turns the
slot traffic into all-to-all collectives), and dropped-token handling
(tokens beyond capacity contribute nothing; the transformer's residual
connection carries them through).

Compute per token is ``k`` experts instead of ``n_experts``; at
``k == n_experts`` with ample capacity the output equals the dense
mixture exactly (parity-tested), because top-k over all experts
renormalizes to the full softmax.

Reference: the blendtorch reference has no model zoo at all (SURVEY.md
§5 long-context: "absent"); this is net-new TPU capability.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from blendjax.models.layers import dense_apply, gelu


def expert_capacity(n_tokens, n_experts, k, capacity_factor):
    """Static per-expert slot count: perfectly balanced load times the
    capacity factor (>=1 leaves headroom for imbalance)."""
    return max(1, math.ceil(k * n_tokens / n_experts * capacity_factor))


def _topk_gates(probs, k):
    """Shared gating prologue — THE one place the routing policy's
    weights live: top-k probabilities renormalized to sum 1, plus the
    choice-major assignment-row expert ids (row ``j*n + i`` is token i's
    j-th choice, so first choices claim capacity slots first).  Both
    dispatch algorithms and the one-hot view build on this; changing the
    renormalization here changes all of them together."""
    n, _ = probs.shape
    gate_w, gate_idx = jax.lax.top_k(probs, k)  # (n, k)
    gate_w = gate_w / jnp.clip(gate_w.sum(-1, keepdims=True), 1e-9, None)
    return gate_idx.T.reshape(k * n), gate_w


def topk_assignments(probs, k, capacity):
    """Top-k routing with capacity-bounded slot assignment, in the
    cumsum (scatter-dispatch) form; shared by the scatter apply path and
    the one-hot matrix view.

    Params
    ------
    probs: (n, e) float32 router probabilities (full softmax).
    k: experts per token.
    capacity: slots per expert (static).

    Returns ``(idx, pos, keep, gate_w)``, all choice-major over ``k*n``
    assignment rows: chosen expert per row, slot index within that
    expert, whether the row won a slot, and the renormalized top-k gate
    weights (n, k).
    """
    n, e = probs.shape
    idx, gate_w = _topk_gates(probs, k)
    oh_i = jax.nn.one_hot(idx, e, dtype=jnp.int32)
    pos = jnp.cumsum(oh_i, axis=0) - oh_i  # prior assignments per expert
    pos = (pos * oh_i).sum(-1)  # (k*n,) slot index within the expert
    keep = pos < capacity
    return idx, pos, keep, gate_w


def route_topk(probs, k, capacity):
    """One-hot matrix view of :func:`topk_assignments` (kept for tests
    and for expressing the dispatch as explicit (k*n, e, capacity)
    tensors; the apply path uses the scatter/gather form directly).

    Returns ``(dispatch, combine, keep)``: one-hot dispatch, dispatch
    scaled by the renormalized gate weight, and the slot-won mask.
    """
    n, e = probs.shape
    idx, pos, keep, gate_w = topk_assignments(probs, k, capacity)
    capacity = int(capacity)
    oh = jax.nn.one_hot(idx, e, dtype=probs.dtype) * keep[:, None]
    slot = jax.nn.one_hot(pos, capacity, dtype=probs.dtype)
    dispatch = oh[:, :, None] * slot[:, None, :]  # (k*n, e, capacity)
    combine = dispatch * gate_w.T.reshape(k * n)[:, None, None]
    return dispatch, combine, keep


def load_balance_loss(probs, gate_idx_top1):
    """Switch-Transformer auxiliary loss: ``e * sum_e(f_e * p_e)`` where
    ``f_e`` is the fraction of tokens whose first choice is expert e and
    ``p_e`` the mean router probability.  Minimized (=1) at uniform load."""
    e = probs.shape[-1]
    f = jax.nn.one_hot(gate_idx_top1, e, dtype=probs.dtype).mean(0)
    p = probs.mean(0)
    return e * jnp.sum(f * p)


def _dispatch_scatter(xf, idx, pos, keep, n, e, d, capacity, dtype):
    """Scatter/gather dispatch: build the arena with ``.at[slot].add``.

    GPU-idiomatic; on TPU the feature-space scatter lowers to a serialized
    dynamic-update-slice chain (VERDICT r3 weak #3) — kept as an option
    for CPU and for parity testing against the sort path.  Returns
    ``(expert_in, row_slot)``: arena rows and each assignment row's slot
    (sentinel ``e*capacity`` when dropped)."""
    k = idx.shape[0] // n
    slot = jnp.where(keep, idx * capacity + pos, e * capacity)  # sentinel
    x_rep = jnp.tile(xf, (k, 1)).astype(dtype)
    arena = jnp.zeros((e * capacity + 1, d), dtype).at[slot].add(x_rep)
    return arena[:-1].reshape(e, capacity, d), slot


def _dispatch_sort(xf, probs, k, capacity, dtype):
    """Sort-based dispatch — the TPU-idiomatic path (VERDICT r3 next #3).

    A *stable* argsort of the choice-major assignment rows by expert id
    groups each expert's assignments contiguously while preserving row
    order within the group, so the within-expert rank equals the cumsum
    slot position of :func:`topk_assignments` exactly (parity-tested).
    The arena is then built with pure GATHERS — slot (q, r) reads sorted
    position ``start[q] + r`` — and the only scatter anywhere is a
    (k*n,) int32 inverse-permutation write.  No feature-space scatter,
    no dynamic-update-slice chains; everything lowers to sorts, gathers
    and matmuls, which XLA tiles onto the TPU's native units.

    Returns ``(expert_in, row_slot, keep, gate_w)``.
    """
    n, e = probs.shape
    idx, gate_w = _topk_gates(probs, k)  # choice-major assignment rows

    order = jnp.argsort(idx, stable=True)  # (k*n,) sorted-pos -> row
    sorted_e = idx[order]
    counts = jnp.bincount(idx, length=e)
    start = jnp.cumsum(counts) - counts  # exclusive prefix: group starts
    rank = jnp.arange(k * n, dtype=jnp.int32) - start[sorted_e]
    keep_sorted = rank < capacity
    slot_sorted = jnp.where(
        keep_sorted, sorted_e * capacity + rank, e * capacity
    )
    # inverse permutation: each assignment row's slot (int32 scatter only)
    row_slot = jnp.zeros((k * n,), jnp.int32).at[order].set(slot_sorted)
    keep = row_slot < e * capacity

    # arena by gather: slot (q, r) <- token of sorted position start[q]+r
    q = jnp.arange(e * capacity, dtype=jnp.int32) // capacity
    r = jnp.arange(e * capacity, dtype=jnp.int32) % capacity
    valid = r < counts[q]
    src = jnp.where(valid, start[q] + r, 0)
    token_for_slot = order[src] % n
    expert_in = jnp.where(
        valid[:, None], xf[token_for_slot].astype(dtype), 0
    ).reshape(e, capacity, xf.shape[-1])
    return expert_in, row_slot, keep, gate_w


def moe_apply_topk(p, x, dtype, k=2, capacity_factor=1.25, dispatch="sort"):
    """Routed MoE layer forward.

    ``p`` is the same parameter pytree as the dense mixture
    (``gate``/``w1``/``b1``/``w2``/``b2`` with expert-stacked weights) —
    routing is an apply-time choice, so checkpoints swap freely between
    dense and routed evaluation.

    ``dispatch`` selects the arena-construction algorithm: ``'sort'``
    (default; contiguous per-expert slices via a stable sort — the TPU
    way, see :func:`_dispatch_sort`) or ``'scatter'``
    (:func:`_dispatch_scatter`).  Both implement the SAME routing policy
    (top-k, capacity-bounded, first-come-first-served choice-major) and
    are parity-tested against each other; compute per token is ``k``
    experts instead of ``n_experts``, dropped tokens ride the residual.

    The combine side is a gather in both cases: each assignment row reads
    its slot's output (a zero sentinel row when dropped) and the k
    contributions sum per token, scaled by the renormalized gate weights.

    Returns ``(y, aux)`` with ``y`` (b, t, d) and ``aux`` a dict carrying
    ``aux_loss`` (load balance) and ``dispatch_fraction`` (1 - dropped).
    """
    b, t, d = x.shape
    n = b * t
    e = p["w1"].shape[0]
    k = min(k, e)
    xf = x.reshape(n, d)

    probs = jax.nn.softmax(dense_apply(p["gate"], xf, dtype=jnp.float32), -1)
    capacity = expert_capacity(n, e, k, capacity_factor)

    if dispatch == "sort":
        expert_in, row_slot, keep, gate_w = _dispatch_sort(
            xf, probs, k, capacity, dtype
        )
    elif dispatch == "scatter":
        idx, pos, keep, gate_w = topk_assignments(probs, k, capacity)
        expert_in, row_slot = _dispatch_scatter(
            xf, idx, pos, keep, n, e, d, capacity, dtype
        )
    else:
        raise ValueError(f"unknown dispatch {dispatch!r}")

    h = gelu(
        jnp.einsum("ecd,edf->ecf", expert_in, p["w1"].astype(dtype))
        + p["b1"][:, None, :].astype(dtype)
    )
    out = jnp.einsum("ecf,efd->ecd", h, p["w2"].astype(dtype))
    out = out + p["b2"][:, None, :].astype(dtype)
    out_flat = jnp.concatenate(
        [out.reshape(e * capacity, d), jnp.zeros((1, d), dtype)]
    )
    scale = (gate_w.T.reshape(k * n) * keep).astype(dtype)
    y = (out_flat[row_slot] * scale[:, None]).reshape(k, n, d).sum(0)
    y = y.reshape(b, t, d)

    aux = {
        "aux_loss": load_balance_loss(probs, jnp.argmax(probs, -1)),
        "dispatch_fraction": keep.astype(jnp.float32).mean(),
    }
    return y, aux
