"""TinyDetector — the flagship supervised model for the datagen workload.

The consumer-side counterpart of ``examples/datagen`` in the reference
(``generate.py`` streams ``image, xy`` pairs; a downstream model regresses
the cube's vertex pixels).  The reference leaves the model to user land;
blendjax ships one, TPU-first: NHWC bfloat16 convs (MXU), static shapes,
global-average-pool head regressing K keypoints in normalized [0,1] image
coordinates.

Pytree layout (for sharding): convs are replicated (small), the two dense
layers carry the parameter mass and shard tensor-parallel over the
``'model'`` mesh axis (see ``blendjax.parallel.sharding.detector_rules``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from blendjax.models.layers import conv_apply, conv_init, dense_apply, dense_init, gelu


def init(key, num_keypoints=8, channels=(32, 64, 128), in_channels=3, hidden=256):
    """Initialize detector params for ``num_keypoints`` (x, y) outputs."""
    keys = jax.random.split(key, len(channels) + 2)
    params = {"convs": []}
    c_in = in_channels
    for i, c_out in enumerate(channels):
        params["convs"].append(conv_init(keys[i], c_in, c_out, ksize=3))
        c_in = c_out
    params["fc"] = dense_init(keys[-2], c_in, hidden)
    params["head"] = dense_init(keys[-1], hidden, num_keypoints * 2)
    return params


def apply(params, images, compute_dtype=jnp.bfloat16, conv_fn=None,
          dense_fn=None):
    """Forward pass.

    Params
    ------
    images: (N, H, W, C) float in [0, 1].
    conv_fn / dense_fn: layer-apply overrides ``(p, x, stride) -> y`` /
        ``(p, x) -> y`` — the seam :mod:`blendjax.ops.quant` injects its
        int8 kernels through, so the architecture lives in exactly one
        place.
    Returns (N, K, 2) predicted keypoints in [0, 1] normalized coordinates.
    """
    if conv_fn is None:
        def conv_fn(p, x, stride):
            return conv_apply(p, x, stride=stride, dtype=compute_dtype)
    if dense_fn is None:
        def dense_fn(p, x):
            return dense_apply(p, x, dtype=compute_dtype)
    x = images.astype(compute_dtype)
    for conv in params["convs"]:
        x = gelu(conv_fn(conv, x, 2))
    x = x.mean(axis=(1, 2))  # global average pool
    x = gelu(dense_fn(params["fc"], x))
    out = dense_fn(params["head"], x)
    k2 = out.shape[-1]
    out = jax.nn.sigmoid(out.astype(jnp.float32))
    return out.reshape(*out.shape[:-1], k2 // 2, 2)


def loss_fn(params, batch, compute_dtype=jnp.bfloat16):
    """MSE over normalized keypoints.

    ``batch`` = {'image': (N,H,W,C) float [0,1], 'xy': (N,K,2) normalized}.
    """
    pred = apply(params, batch["image"], compute_dtype)
    err = pred - batch["xy"].astype(jnp.float32)
    return jnp.mean(err * err)


def train_flops(batch_size, height, width, num_keypoints=8,
                channels=(32, 64, 128), in_channels=3, hidden=256):
    """Closed-form FLOPs of one training step (matmul/conv terms only).

    Forward: each stride-2 SAME conv is ``2 * B*Ho*Wo * 9 * Cin * Cout``
    FLOPs; the two dense layers are ``2 * B * in * out``.  Training
    counts forward + backward as 3x forward (the backward pass does two
    matmul-shaped products per forward product); elementwise ops and the
    optimizer are omitted (<1% at these shapes).  Used by the benchmark
    suite to cross-check XLA's ``cost_analysis()`` (VERDICT r3 next #2).
    """
    fwd = 0.0
    h, w, c_in = height, width, in_channels
    for c_out in channels:
        h, w = (h + 1) // 2, (w + 1) // 2
        fwd += 2.0 * batch_size * h * w * 9 * c_in * c_out
        c_in = c_out
    fwd += 2.0 * batch_size * c_in * hidden
    fwd += 2.0 * batch_size * hidden * num_keypoints * 2
    return 3.0 * fwd
