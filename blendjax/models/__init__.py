"""TPU-first model zoo for the blendjax workloads.

- ``detector``      — keypoint CNN for the datagen workload (flagship).
- ``discriminator`` — real/fake image scorer for densityopt.
- ``probmodel``     — log-normal sim-parameter model + score-function grads.
- ``policy``        — MLP policies + REINFORCE/PPO (critic, GAE,
                      clipped surrogate) for the control workload.
- ``seqformer``     — causal temporal transformer (world-model) over
                      episode sequences; long-context flagship (ring/
                      Ulysses sequence parallel, sliding window, GQA,
                      learned or rotary positions, optional MoE;
                      KV-cache ``rollout`` for open-loop dreaming).
- ``train``         — TrainState + jitted/donated train-step builders.
"""

from blendjax.models import (
    detector,
    discriminator,
    layers,
    policy,
    probmodel,
    seqformer,
    train,
)
from blendjax.models.train import TrainState, make_eval_step, make_train_step

__all__ = [
    "detector",
    "discriminator",
    "layers",
    "policy",
    "probmodel",
    "seqformer",
    "train",
    "TrainState",
    "make_train_step",
    "make_eval_step",
]
