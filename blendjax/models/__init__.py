"""TPU-first model zoo for the blendjax workloads.

- ``detector``      — keypoint CNN for the datagen workload (flagship).
- ``discriminator`` — real/fake image scorer for densityopt.
- ``probmodel``     — log-normal sim-parameter model + score-function grads.
- ``policy``        — MLP policies + REINFORCE for the control workload.
- ``train``         — TrainState + jitted/donated train-step builders.
"""

from blendjax.models import detector, discriminator, layers, policy, probmodel, train
from blendjax.models.train import TrainState, make_eval_step, make_train_step

__all__ = [
    "detector",
    "discriminator",
    "layers",
    "policy",
    "probmodel",
    "train",
    "TrainState",
    "make_train_step",
    "make_eval_step",
]
