"""Patch discriminator for the densityopt workload.

Counterpart of the reference's DCGAN-style torch Discriminator
(``examples/densityopt/densityopt.py:139-190``) that scores rendered
supershape images as real/fake; its loss on simulated images is the signal
the score-function estimator pushes back into Blender's scene parameters.
TPU-first: strided NHWC bfloat16 convs, no batchnorm (leaky-ReLU +
layer-scale keeps it SPMD-trivial: no cross-device batch statistics).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from blendjax.models.layers import conv_apply, conv_init, dense_apply, dense_init


def init(key, in_channels=1, widths=(32, 64, 128)):
    keys = jax.random.split(key, len(widths) + 1)
    params = {"convs": []}
    c_in = in_channels
    for i, c_out in enumerate(widths):
        params["convs"].append(conv_init(keys[i], c_in, c_out, ksize=4))
        c_in = c_out
    params["head"] = dense_init(keys[-1], c_in, 1)
    return params


def apply(params, images, compute_dtype=jnp.bfloat16):
    """(N, H, W, C) float -> (N,) real/fake logits."""
    x = images.astype(compute_dtype)
    for conv in params["convs"]:
        x = jax.nn.leaky_relu(conv_apply(conv, x, stride=2, dtype=compute_dtype), 0.2)
    x = x.mean(axis=(1, 2))
    return dense_apply(params["head"], x, dtype=compute_dtype).astype(jnp.float32)[..., 0]


def bce_logits(logits, targets):
    """Numerically-stable binary cross entropy on logits."""
    return jnp.mean(
        jnp.maximum(logits, 0.0) - logits * targets + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    )


def d_loss_fn(params, real_images, fake_images, compute_dtype=jnp.bfloat16):
    """Discriminator loss: real -> 1, simulated -> 0."""
    logits_real = apply(params, real_images, compute_dtype)
    logits_fake = apply(params, fake_images, compute_dtype)
    return bce_logits(logits_real, jnp.ones_like(logits_real)) + bce_logits(
        logits_fake, jnp.zeros_like(logits_fake)
    )


def sim_scores(params, fake_images, compute_dtype=jnp.bfloat16):
    """Per-sample 'fool the discriminator' losses for the score-function
    estimator: -log D(fake)."""
    logits = apply(params, fake_images, compute_dtype)
    return jnp.maximum(logits, 0.0) - logits + jnp.log1p(jnp.exp(-jnp.abs(logits)))
