"""Sebulba-style decoupled actor/learner for remote Blender fleets.

Podracer architectures (arXiv:2104.06272) split RL into an *actor* that
steps environments and a *learner* that updates parameters, running
concurrently with a trajectory queue between them.  That split fits
blendjax exactly: Blender env steps are host-bound RPCs
(``EnvPool.step`` — REQ/REP into the fleet's animation loops), while the
policy update is device-bound XLA — interleaving them serially (the
reference's only mode, and ``train_reinforce.py``'s) idles each side
half the time.  Here the actor thread keeps the fleet stepping at full
RPC rate with jitted policy inference on parameter snapshots while the
learner consumes fixed-length trajectory segments and publishes fresh
params; staleness is bounded by the queue depth (actor policy lags the
learner by at most ``queue_size`` updates — standard Sebulba trade).

No reference counterpart (its RL story is one blocking env,
``pkg_pytorch/blendtorch/btt/env.py``); net-new capability like the
SeqFormer stack.
"""

from __future__ import annotations

import logging
import queue
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from blendjax.models import policy
from blendjax.models.train import TrainState, make_train_step

log = logging.getLogger("blendjax")


class ActorLearner:
    """Overlapped actor/learner REINFORCE over an :class:`EnvPool`.

    Params
    ------
    pool: EnvPool
        Connected fleet (autoreset recommended); the caller owns it.
    obs_dim, num_actions: int
        Policy sizes (``continuous=True`` for a Gaussian head).
    rollout_len: int
        Steps per trajectory segment (the queue's unit of work).
    queue_size: int
        Segments buffered between actor and learner — also the bound on
        actor policy staleness, in updates.
    action_map: callable | None
        Maps the sampled action array (shape (N,)) to the list the
        producers expect (e.g. discrete index -> motor force).
    pipeline: bool
        Double-buffer rollout collection over the pool's async path
        (``step_async``/``step_wait_full``): actions are submitted first
        and the fleet simulates frame t+1 while the actor finalizes the
        previous segment (the ``np.stack`` + queue handoff — including
        any block on a full queue — happens inside the simulation
        window).  False keeps the lock-step ``pool.step`` loop.
    replay: blendjax.replay.ReplayBuffer | None
        Off-policy path (docs/replay.md): the actor thread appends every
        transition — quarantine-aware, so a degraded rollout's synthetic
        transitions land flagged and are never sampled — and the learner
        follows each on-policy update with ``replay_ratio`` sampled
        off-policy updates (importance-weighted single-step policy
        gradient, priorities refreshed from |advantage|).  A prefilled
        buffer also trains with no fleet at all via :meth:`run_offline`.
    replay_ratio: int
        Off-policy updates per on-policy update (0 = append-only: the
        buffer fills for later offline runs/checkpoints).
    replay_batch: int
        Transitions per off-policy update.
    """

    def __init__(self, pool, obs_dim, num_actions, *, rollout_len=32,
                 queue_size=4, optimizer=None, gamma=0.99, seed=0,
                 continuous=False, action_map=None, pipeline=False,
                 replay=None, replay_ratio=0, replay_batch=64):
        self.pool = pool
        self.rollout_len = rollout_len
        self.gamma = gamma
        self.continuous = continuous
        self.pipeline = bool(pipeline)
        self.action_map = action_map or (lambda a: list(np.asarray(a)))
        params = policy.init(
            jax.random.PRNGKey(seed), obs_dim, num_actions,
            continuous=continuous,
        )
        self.opt = optimizer or optax.adam(3e-3)
        self.state = TrainState.create(params, self.opt)
        self._seed = seed
        #: snapshot the actor samples from; swapped atomically (CPython
        #: attribute assignment) by the learner after each update
        self._actor_params = params

        def _sample_step(params, key, obs):
            # one jitted dispatch per env step: key advance + sampling
            # fused (a separate jax.random.split call would double the
            # per-step dispatch overhead, which dominates on small hosts)
            key, sub = jax.random.split(key)
            action, logp = policy.sample_action(params, sub, obs)
            return action, logp, key

        self._sample = jax.jit(_sample_step)

        def loss_fn(p, batch):
            returns = policy.discounted_returns(
                batch["rewards"], batch["dones"], gamma
            )
            t, n = batch["rewards"].shape
            return policy.reinforce_loss(
                p,
                batch["obs"].reshape(t * n, -1),
                batch["actions"].reshape(t * n, *batch["actions"].shape[2:]),
                returns.reshape(t * n),
                continuous=continuous,
            )

        # donate=False ON PURPOSE: the actor thread samples from a params
        # snapshot that must survive the next update; donating the state
        # would invalidate the snapshot's buffers under the actor's feet
        self._step = make_train_step(loss_fn, self.opt, donate=False)

        self.replay = replay
        self.replay_ratio = int(replay_ratio)
        self.replay_batch = int(replay_batch)
        if replay_ratio and replay is None:
            raise ValueError("replay_ratio > 0 requires a replay buffer")

        def replay_loss_fn(p, batch):
            # importance-weighted single-step policy gradient over
            # sampled transitions: logp of the STORED action under the
            # CURRENT policy, advantage = batch-normalized reward,
            # weighted by the sampler's IS weights (PER bias correction)
            if continuous:
                logp = policy.gaussian_log_prob(
                    p, batch["obs"], batch["action"]
                )
            else:
                logp = policy.categorical_log_prob(
                    p, batch["obs"], batch["action"]
                )
            r = batch["reward"]
            adv = (r - r.mean()) / (r.std() + 1e-6)
            return -jnp.mean(
                batch["is_weight"] * logp * jax.lax.stop_gradient(adv)
            )

        self._replay_step = (
            make_train_step(replay_loss_fn, self.opt, donate=False)
            if replay is not None
            else None
        )
        self._q: queue.Queue = queue.Queue(maxsize=queue_size)
        self._stop = threading.Event()
        self._thread = None
        self._actor_error = None
        self._env_steps = 0
        self._unhealthy_env_steps = 0
        self._degraded = False

    # -- actor side --------------------------------------------------------

    def _enqueue_segment(self, seg_lists):
        """Stack a finished segment and hand it to the learner (bounded
        put, re-checked against stop).  Returns False once stop is set."""
        seg = tuple(np.stack(col) for col in seg_lists)
        while not self._stop.is_set():
            try:
                self._q.put(seg, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _actor(self):
        try:
            # derived from the constructor seed: runs are reproducible
            rng = jax.random.fold_in(
                jax.random.PRNGKey(self._seed), 0xAC708
            )
            obs, _ = self.pool.reset()
            obs = np.asarray(obs, np.float32)
            if obs.ndim == 1:
                obs = obs[:, None]
            pending_seg = None  # finished segment owed to the learner
            while not self._stop.is_set():
                seg_obs, seg_act, seg_rew, seg_done = [], [], [], []
                params = self._actor_params  # snapshot for whole segment
                for _ in range(self.rollout_len):
                    action, _logp, rng = self._sample(params, rng, obs)
                    action = np.asarray(action)
                    if self.pipeline:
                        # double-buffer: submit first, so the fleet
                        # simulates frame t+1 while this thread finalizes
                        # segment t (the stack + queue handoff below can
                        # even block on a full queue — the envs keep
                        # integrating physics through the stall)
                        self.pool.step_async(self.action_map(action))
                        if pending_seg is not None:
                            if not self._enqueue_segment(pending_seg):
                                # stop arrived with a batch in flight:
                                # drain it so the pool is reusable for
                                # lock-step callers after run() returns
                                self.pool.step_wait()
                                return
                            pending_seg = None
                        nobs, rew, done, infos = self.pool.step_wait_full()
                    else:
                        nobs, rew, done, infos = self.pool.step(
                            self.action_map(action)
                        )
                    # degraded-mode accounting: quarantined slots return
                    # synthetic zero-reward transitions (see
                    # docs/fault_tolerance.md) — surface how much of the
                    # rollout they make up instead of absorbing it silently
                    unhealthy = sum(
                        1 for inf in infos if not inf.get("healthy", True)
                    )
                    if unhealthy:
                        self._unhealthy_env_steps += unhealthy
                        if not self._degraded:
                            self._degraded = True
                            log.warning(
                                "actor rollout degraded: %d/%d envs "
                                "quarantined (synthetic transitions in "
                                "the batch)", unhealthy, self.pool.num_envs,
                            )
                    elif self._degraded:
                        self._degraded = False
                        log.warning("actor rollout healthy again")
                    seg_obs.append(obs)
                    seg_act.append(action)
                    seg_rew.append(np.asarray(rew, np.float32))
                    seg_done.append(np.asarray(done, bool))
                    prev_obs = obs
                    obs = np.asarray(nobs, np.float32)
                    if obs.ndim == 1:
                        obs = obs[:, None]
                    if self.replay is not None:
                        # quarantine-aware appends: a synthetic transition
                        # from a quarantined slot is stored flagged and
                        # never sampled (docs/replay.md)
                        self.replay.extend(
                            (
                                {
                                    "obs": prev_obs[i],
                                    "action": action[i],
                                    "reward": seg_rew[-1][i],
                                    "next_obs": obs[i],
                                    "done": seg_done[-1][i],
                                }
                                for i in range(self.pool.num_envs)
                            ),
                            healthy=[
                                inf.get("healthy", True) for inf in infos
                            ],
                        )
                    self._env_steps += self.pool.num_envs
                seg_lists = (seg_obs, seg_act, seg_rew, seg_done)
                if self.pipeline:
                    # deferred into the next submission's simulation window
                    pending_seg = seg_lists
                else:
                    if not self._enqueue_segment(seg_lists):
                        return
        except BaseException as exc:  # noqa: BLE001 - surfaced by learner
            self._actor_error = exc
            self._stop.set()

    # -- learner side ------------------------------------------------------

    def _replay_step_and_refresh(self, batch, idx, reward):
        """The shared off-policy post-draw block (online tail AND
        run_offline): one sampled update, actor params mirror, and the
        sampled rows' priorities refreshed from |advantage| under the
        batch baseline (the same signal the loss weights)."""
        self.state, loss = self._replay_step(self.state, batch)
        self._actor_params = self.state.params
        r = np.asarray(reward, np.float64)
        self.replay.update_priorities(idx, np.abs(r - r.mean()))
        return float(loss)

    def _replay_update(self, data, idx, weights):
        """One off-policy update from a host-side sampled batch."""
        batch = jax.device_put(
            {
                "obs": data["obs"],
                "action": data["action"],
                "reward": data["reward"],
                "is_weight": weights,
            }
        )
        return self._replay_step_and_refresh(batch, idx, data["reward"])

    def _drain_replay_ratio(self, replay_losses):
        """The learner's off-policy tail: up to ``replay_ratio`` sampled
        updates, skipped (not blocked on) while the buffer is short —
        early in training the on-policy path must keep moving.
        ``timeout=0`` makes the shortfall check and the draw one atomic
        step (a pre-check of ``num_eligible`` could pass and then a
        degraded fleet's unhealthy appends evict the eligible rows
        before the draw acquired the lock, blocking the learner)."""
        for _ in range(self.replay_ratio):
            try:
                data, idx, w = self.replay.sample(
                    self.replay_batch, timeout=0.0,
                    keys=("obs", "action", "reward"),
                )
            except TimeoutError:
                return
            replay_losses.append(self._replay_update(data, idx, w))

    def run_offline(self, num_updates, batch_size=64, *, arena_pool=None,
                    prefetch=2):
        """Train purely from the replay buffer — zero Blender processes
        (e.g. after :func:`blendjax.replay.prefill_from_btr`).

        Sampled batches are gathered straight into recycled
        :class:`~blendjax.btt.arena.ArenaPool` buffers and staged onto
        the device through ``device_prefetch`` — the PR-1 feed seam,
        driven by the sampler instead of the wire; sampling for batch
        t+1 overlaps the update on batch t.  Returns a stats dict.
        """
        from blendjax.btt.arena import ArenaPool
        from blendjax.btt.prefetch import device_prefetch

        if self.replay is None:
            raise RuntimeError("run_offline requires a replay buffer")
        pool = arena_pool or ArenaPool(pool_size=prefetch + 2)
        stop = threading.Event()
        gen = self.replay.sample_batches(
            batch_size, arena_pool=pool, stop_event=stop,
            # gather (and transfer) only what the off-policy loss and
            # the priority refresh read — next_obs/done alone would
            # double the per-batch copy volume for image observations
            keys=("obs", "action", "reward"),
        )
        losses = []
        t0 = time.perf_counter()
        it = device_prefetch(
            gen, size=prefetch, timer=self.replay.timer
        )
        try:
            for dev_batch in it:
                # sidecar meta came back in-band (the prefetcher unwraps
                # ArenaBatch), keying the priority refresh
                losses.append(self._replay_step_and_refresh(
                    {
                        "obs": dev_batch["obs"],
                        "action": dev_batch["action"],
                        "reward": dev_batch["reward"],
                        "is_weight": dev_batch["is_weight"],
                    },
                    np.asarray(dev_batch["replay_idx"]),
                    np.asarray(dev_batch["reward"]),
                ))
                if len(losses) >= num_updates:
                    break
        finally:
            stop.set()
            it.close()
        elapsed = time.perf_counter() - t0
        return {
            "updates": len(losses),
            "updates_per_sec": round(len(losses) / elapsed, 2),
            "losses": losses,
            "replay": self.replay.stats(),
            "elapsed_s": round(elapsed, 3),
        }

    def run(self, num_updates=None, seconds=None):
        """Run the overlapped loop for ``num_updates`` learner steps OR a
        ``seconds`` wall-clock budget (whichever is given; both = either
        limit ends the run); returns a stats dict.

        Re-runnable: each call gets a fresh stop event, a zeroed step
        counter, and an emptied queue (a previous run's buffered segments
        carry a stale policy and would also corrupt the throughput math).
        """
        if self.pool is None:
            # constructible fleet-less for the pure off-policy path
            # (prefilled replay buffer): that path is run_offline()
            raise RuntimeError(
                "no EnvPool attached; use run_offline() to train from "
                "the replay buffer"
            )
        if num_updates is None and seconds is None:
            raise ValueError("pass num_updates and/or seconds")
        if self._thread is not None and self._thread.is_alive():
            # a leaked actor (previous run's join timed out on a stalled
            # RPC) sharing the REQ sockets with a fresh one would corrupt
            # the zmq protocol and double-count env steps
            raise RuntimeError(
                "previous run's actor thread is still alive; close the "
                "pool or wait before re-running"
            )
        self._stop = threading.Event()
        self._actor_error = None
        self._env_steps = 0
        self._unhealthy_env_steps = 0
        self._degraded = False
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread = threading.Thread(
            target=self._actor, daemon=True, name="bjx-actor"
        )
        t0 = time.perf_counter()
        deadline = t0 + seconds if seconds is not None else None
        self._thread.start()
        losses, seg_rewards, replay_losses = [], [], []
        try:
            while True:
                if num_updates is not None and len(losses) >= num_updates:
                    break
                if deadline is not None and time.perf_counter() >= deadline:
                    break
                while True:
                    if self._actor_error is not None:
                        raise RuntimeError(
                            "actor thread failed"
                        ) from self._actor_error
                    try:
                        seg = self._q.get(timeout=0.5)
                        break
                    except queue.Empty:
                        if (deadline is not None
                                and time.perf_counter() >= deadline):
                            seg = None
                            break
                if seg is None:
                    break
                batch = jax.device_put(
                    {"obs": seg[0], "actions": seg[1],
                     "rewards": seg[2], "dones": seg[3]}
                )
                self.state, loss = self._step(self.state, batch)
                self._actor_params = self.state.params
                losses.append(float(loss))
                seg_rewards.append(float(seg[2].mean()))
                if self.replay is not None and self.replay_ratio > 0:
                    self._drain_replay_ratio(replay_losses)
        finally:
            self._stop.set()
            self._thread.join(timeout=10)
        elapsed = time.perf_counter() - t0
        stats = {
            "updates": len(losses),
            "env_steps": self._env_steps,
            "unhealthy_env_steps": self._unhealthy_env_steps,
            "env_steps_per_sec": round(self._env_steps / elapsed, 1),
            "updates_per_sec": round(len(losses) / elapsed, 2),
            "first_segment_reward": seg_rewards[0] if seg_rewards else None,
            "last_segment_reward": seg_rewards[-1] if seg_rewards else None,
            "segment_rewards": seg_rewards,
            "losses": losses,
            "elapsed_s": round(elapsed, 3),
        }
        if self.replay is not None:
            stats["replay_updates"] = len(replay_losses)
            stats["replay_losses"] = replay_losses
            stats["replay"] = self.replay.stats()
        return stats
