"""Sebulba-style decoupled actor/learner for remote Blender fleets.

Podracer architectures (arXiv:2104.06272) split RL into an *actor* that
steps environments and a *learner* that updates parameters, running
concurrently with a trajectory queue between them.  That split fits
blendjax exactly: Blender env steps are host-bound RPCs
(``EnvPool.step`` — REQ/REP into the fleet's animation loops), while the
policy update is device-bound XLA — interleaving them serially (the
reference's only mode, and ``train_reinforce.py``'s) idles each side
half the time.  Here the actor thread keeps the fleet stepping at full
RPC rate with jitted policy inference on parameter snapshots while the
learner consumes fixed-length trajectory segments and publishes fresh
params; staleness is bounded by the queue depth (actor policy lags the
learner by at most ``queue_size`` updates — standard Sebulba trade).

The **sharded configuration** (``mesh=``, ``num_fleets=``; see
docs/sharded_rl.md) scales both halves horizontally: N actor threads —
one per :class:`~blendjax.btt.envpool.EnvPool` fleet, each fleet with
its own :class:`~blendjax.btt.supervise.FleetSupervisor` and port range
(:class:`~blendjax.parallel.podracer.FleetSet`) — fan their rollout
segments into ONE env-major global batch per update
(:class:`~blendjax.parallel.podracer.SegmentFanIn`: arena-pooled
assembly, divisibility padding + mask), which lands **pre-sharded along
the batch axis** (``NamedSharding(mesh, P('data'))``) under a learner
whose params are mesh-replicated, so XLA inserts the gradient psum over
the mesh on its own.  A fleet that dies entirely is zero-masked out of
the batch instead of stalling the learner; the replay off-policy tail
shards its sampled batches identically.

No reference counterpart (its RL story is one blocking env,
``pkg_pytorch/blendtorch/btt/env.py``); net-new capability like the
SeqFormer stack.
"""

from __future__ import annotations

import logging
import queue
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from blendjax.models import policy
from blendjax.models.train import TrainState, make_train_step
from blendjax.obs.flight import flight_recorder

log = logging.getLogger("blendjax")


def _as_pools(pool):
    """Normalize the ``pool`` argument: one EnvPool, a sequence of them
    (one per fleet), a :class:`~blendjax.parallel.podracer.FleetSet`, or
    None (fleet-less, for :meth:`ActorLearner.run_offline`)."""
    if pool is None:
        return []
    if hasattr(pool, "pools"):  # FleetSet
        return list(pool.pools)
    if isinstance(pool, (list, tuple)):
        return list(pool)
    return [pool]


class ActorLearner:
    """Overlapped actor/learner REINFORCE over one or more
    :class:`EnvPool` fleets.

    Params
    ------
    pool: EnvPool | sequence[EnvPool] | FleetSet | None
        Connected fleet(s) (autoreset recommended); the caller owns
        them.  A sequence (or a
        :class:`~blendjax.parallel.podracer.FleetSet`) runs one actor
        thread per fleet with the segments fanned into a single global
        batch per update.  None is allowed for the pure off-policy path
        (:meth:`run_offline`) and for mesh-only construction (tests).
    obs_dim, num_actions: int
        Policy sizes (``continuous=True`` for a Gaussian head).
    rollout_len: int
        Steps per trajectory segment (the queue's unit of work).
    queue_size: int
        Segments buffered between actor and learner — also the bound on
        actor policy staleness, in updates.
    action_map: callable | None
        Maps the sampled action array (shape (N,)) to the list the
        producers expect (e.g. discrete index -> motor force).
    pipeline: bool
        Double-buffer rollout collection over each pool's async path
        (``step_async``/``step_wait_full``): actions are submitted first
        and the fleet simulates frame t+1 while the actor finalizes the
        previous segment (the segment stack + queue handoff — including
        any block on a full queue — happens inside the simulation
        window).  False keeps the lock-step ``pool.step`` loop.
    mesh: jax.sharding.Mesh | None
        Sebulba sharded learner: params replicate over the mesh, rollout
        batches (and sampled replay batches) arrive sharded ``P('data')``
        along the batch axis, and XLA lays the gradient psum over the
        mesh.  Requires the env-major fan-in path (enabled automatically;
        a single fleet over a mesh works too).
    num_fleets: int | None
        Validation/intent marker for the multi-fleet configuration; when
        given it must match the number of pools passed.
    replay: blendjax.replay.ReplayBuffer | ShardedReplay | None
        Off-policy path (docs/replay.md): the actor threads append every
        transition — quarantine-aware, so a degraded rollout's synthetic
        transitions land flagged and are never sampled — and the learner
        follows each on-policy update with ``replay_ratio`` sampled
        off-policy updates (importance-weighted single-step policy
        gradient, priorities refreshed from |advantage|).  A prefilled
        buffer also trains with no fleet at all via :meth:`run_offline`.
        A :class:`~blendjax.replay.ShardedReplay` (the replay *service*,
        docs/replay.md "Sharded replay service") drops in transparently:
        same sample/append surface, and a shard outage degrades the
        off-policy tail (draws renormalize over live shards; a fully
        starved draw is skipped and counted ``replay_sample_skips``)
        instead of failing training — the storage tier survives faults
        the same way the fleet does.
    replay_ratio: int
        Off-policy updates per on-policy update (0 = append-only: the
        buffer fills for later offline runs/checkpoints).
    replay_batch: int
        Transitions per off-policy update; under ``mesh=`` it must
        divide by the mesh's data-axis size (sampled batches shard the
        same way the rollout batches do).
    hub: blendjax.obs.TelemetryHub | None
        Register the training loop's telemetry sources (the replay
        buffer's counters/timer when one is attached, plus a
        ``stats``-shaped probe over the fleet/step accounting) so one
        ``hub.scrape()`` covers acting AND learning.
    weight_bus: blendjax.weights.WeightPublisher | None
        Live weight publication to the serve tier (docs/weight_bus.md):
        every ``publish_every``-th completed update (on-policy AND
        off-policy — whatever advanced the params) snapshots the
        learner params onto the bus as a versioned, checksummed
        snapshot; subscribed :class:`~blendjax.serve.server.
        PolicyServer` replicas hot-swap it between ticks.  The caller
        owns the publisher (and its ``quantize=`` choice must match
        the serving precision).
    publish_every: int
        Updates between bus publishes (1 = every update).
    scenarios: blendjax.scenario.DomainRandomizer | None
        The scenario plane (docs/scenarios.md): transitions are
        stamped with their env's scenario (the producer's in-band echo,
        falling back to the fleet's assignment), per-scenario env-step
        and update counts accumulate (:meth:`stats`), re-admitted envs
        get their scenario re-pushed, and replay appends carry the
        stamp into per-scenario strata.  None (the default) changes
        NOTHING — runs without a scenario plane are byte-identical.
    curriculum: blendjax.scenario.CurriculumScheduler | None
        Ticked once per completed update: on its interval it reweights
        the scenario mix from the replay strata and (when
        ``scenarios`` is attached) drives the new per-fleet assignment
        through the randomizer — a curriculum shift is visible from
        the training loop alone via :meth:`stats`.
    fanin_min_ready: int | None
        Heterogeneous-fleet fan-in (multi-fleet only): collect a
        global batch as soon as this many live fleets contributed a
        segment (absent fleets zero-masked) instead of barriering on
        every live fleet — what keeps a slow rich scenario from
        stalling the learner.  None keeps the all-live barrier.
    """

    def __init__(self, pool, obs_dim, num_actions, *, rollout_len=32,
                 queue_size=4, optimizer=None, gamma=0.99, seed=0,
                 continuous=False, action_map=None, pipeline=False,
                 mesh=None, num_fleets=None,
                 replay=None, replay_ratio=0, replay_batch=64, hub=None,
                 weight_bus=None, publish_every=1,
                 scenarios=None, curriculum=None, fanin_min_ready=None,
                 checkpointer=None, pipeline_stages=None,
                 pipeline_microbatches=None):
        self.pools = _as_pools(pool)
        if num_fleets is not None:
            if self.pools and num_fleets != len(self.pools):
                raise ValueError(
                    f"num_fleets={num_fleets} but {len(self.pools)} pools "
                    "were passed — pass one EnvPool per fleet (e.g. via "
                    "blendjax.parallel.podracer.FleetSet)"
                )
        self.num_fleets = len(self.pools) or (num_fleets or 0)
        #: first pool, kept for single-fleet back-compat call sites
        self.pool = self.pools[0] if self.pools else None
        self.rollout_len = rollout_len
        self.queue_size = queue_size
        self.gamma = gamma
        self.continuous = continuous
        self.pipeline = bool(pipeline)
        self.action_map = action_map or (lambda a: list(np.asarray(a)))
        self.mesh = mesh
        #: env-major fan-in path: any mesh, or more than one fleet
        self._use_fanin = mesh is not None or len(self.pools) > 1
        params = policy.init(
            jax.random.PRNGKey(seed), obs_dim, num_actions,
            continuous=continuous,
        )
        self.opt = optimizer or optax.adam(3e-3)
        self._seed = seed
        self._batch_sharding = None
        self._actor_device = None
        if mesh is not None:
            from blendjax.parallel.mesh import data_sharding
            from blendjax.parallel.sharding import param_specs, shard_pytree

            if "data" not in mesh.shape:
                raise ValueError(f"mesh {mesh} has no 'data' axis")
            self._batch_sharding = data_sharding(mesh)
            self._data_size = int(mesh.shape["data"])
            #: actors sample on ONE (default) device — an SPMD dispatch
            #: over the whole mesh per env step costs ~10x more than the
            #: tiny policy computes; the learner gathers a snapshot per
            #: update.  UNCOMMITTED arrays on purpose: a device-committed
            #: input pytree disables jit's default-device fast dispatch
            #: path (measured ~3-6x per-call overhead on a small host),
            #: and the actors dispatch once per env step
            self._actor_device = True  # marker: gather snapshots
            self._actor_params = jax.tree.map(
                jnp.asarray, jax.device_get(params)
            )
            # replicate params over the mesh (rules={} -> every leaf P());
            # the sharded BATCH is what makes XLA psum the gradients
            params = shard_pytree(params, mesh, param_specs(params, {}))
        else:
            self._data_size = 1
            self._actor_params = params
        self.state = TrainState.create(params, self.opt)

        def _sample_step(params, key, obs):
            # one jitted dispatch per env step: key advance + sampling
            # fused (a separate jax.random.split call would double the
            # per-step dispatch overhead, which dominates on small hosts)
            key, sub = jax.random.split(key)
            action, logp = policy.sample_action(params, sub, obs)
            return action, logp, key

        self._sample = jax.jit(_sample_step)

        if self._use_fanin:
            from blendjax.parallel.podracer import make_segment_loss

            loss_fn = make_segment_loss(gamma, continuous=continuous)
        else:
            def loss_fn(p, batch):
                returns = policy.discounted_returns(
                    batch["rewards"], batch["dones"], gamma
                )
                t, n = batch["rewards"].shape
                return policy.reinforce_loss(
                    p,
                    batch["obs"].reshape(t * n, -1),
                    batch["actions"].reshape(
                        t * n, *batch["actions"].shape[2:]
                    ),
                    returns.reshape(t * n),
                    continuous=continuous,
                )

        # donate=False ON PURPOSE: the actor thread samples from a params
        # snapshot that must survive the next update; donating the state
        # would invalidate the snapshot's buffers under the actor's feet
        self._step = make_train_step(loss_fn, self.opt, donate=False)

        self.replay = replay
        self.replay_ratio = int(replay_ratio)
        self.replay_batch = int(replay_batch)
        if replay_ratio and replay is None:
            raise ValueError("replay_ratio > 0 requires a replay buffer")
        if mesh is not None and replay is not None \
                and self.replay_batch % self._data_size:
            raise ValueError(
                f"replay_batch={self.replay_batch} does not divide over "
                f"the mesh's data axis ({self._data_size} shards); pick "
                "batch sizes divisible by the mesh axes they shard over"
            )

        def replay_loss_fn(p, batch):
            # importance-weighted single-step policy gradient over
            # sampled transitions: logp of the STORED action under the
            # CURRENT policy, advantage = batch-normalized reward,
            # weighted by the sampler's IS weights (PER bias correction)
            if continuous:
                logp = policy.gaussian_log_prob(
                    p, batch["obs"], batch["action"]
                )
            else:
                logp = policy.categorical_log_prob(
                    p, batch["obs"], batch["action"]
                )
            r = batch["reward"]
            adv = (r - r.mean()) / (r.std() + 1e-6)
            return -jnp.mean(
                batch["is_weight"] * logp * jax.lax.stop_gradient(adv)
            )

        self._replay_step = (
            make_train_step(replay_loss_fn, self.opt, donate=False)
            if replay is not None
            else None
        )
        #: MPMD pipeline-parallel learner mode (docs/pipeline.md): the
        #: off-policy update runs on an N-stage process fleet through a
        #: :class:`~blendjax.parallel.mpmd.MpmdTrain` driver instead of
        #: the in-process ``_replay_step``.  The driver's ``pg`` family
        #: computes THE SAME importance-weighted loss — advantage
        #: batch-normalized over the FULL batch host-side, so equal
        #: microbatch means average to ``replay_loss_fn`` exactly (the
        #: mpmd numerics tests lock it against ``make_pipeline_train``).
        self.pipeline_driver = pipeline_stages
        self.pipeline_microbatches = pipeline_microbatches
        if pipeline_stages is not None:
            if mesh is not None:
                raise ValueError(
                    "pipeline_stages= and mesh= are different parallel "
                    "axes of the learner; pass one"
                )
            if pipeline_stages.spec["family"] != "pg":
                raise ValueError(
                    "pipeline_stages= needs an MpmdTrain with "
                    "family='pg' (the learner's off-policy loss); got "
                    f"{pipeline_stages.spec['family']!r}"
                )
            if continuous:
                raise ValueError(
                    "pipeline_stages= supports discrete policies only "
                    "(the pg stage loss is categorical)"
                )
            if (pipeline_stages.spec["d_in"], pipeline_stages.spec["d_out"]) \
                    != (obs_dim, num_actions):
                raise ValueError(
                    f"pipeline spec d_in/d_out "
                    f"({pipeline_stages.spec['d_in']}, "
                    f"{pipeline_stages.spec['d_out']}) != learner "
                    f"obs_dim/num_actions ({obs_dim}, {num_actions})"
                )
            if self.pipeline_microbatches is None:
                self.pipeline_microbatches = \
                    pipeline_stages.spec["n_procs"]
            # the stage fleet owns the authoritative params (restored
            # from its own checkpoints across respawns): adopt them, so
            # actor sampling / bus publishes / checkpoints mirror the
            # fleet instead of forking a second lineage from `seed`
            self._adopt_pipeline_params()
        self.weight_bus = weight_bus
        self.publish_every = max(1, int(publish_every))
        #: last version id this learner published on the bus (None
        #: before the first publish) — checkpointed so a restored
        #: learner's resume republish provably rolls the serve tier
        #: FORWARD past it (docs/fault_tolerance.md "Learner failover")
        self.last_published_version = None
        #: coordinated train-state checkpointing (blendjax.ha): one
        #: maybe_checkpoint per completed update, from the learner
        #: thread — the synchronous barrier the checkpointer charges
        #: training is bounded and measured (``ha_snapshot``)
        self.checkpointer = checkpointer
        #: scenario plane (docs/scenarios.md); None = plane off, and
        #: every scenario-aware branch below is skipped — plane-off
        #: runs stay byte-identical to pre-scenario builds
        self.randomizer = scenarios
        self.curriculum = curriculum
        self.fanin_min_ready = (
            None if fanin_min_ready is None else max(1, int(fanin_min_ready))
        )
        nf = max(1, len(self.pools) or (num_fleets or 0) or 1)
        # per-fleet dicts: each is written by exactly one actor thread
        self._scenario_steps_by_fleet = [dict() for _ in range(nf)]
        self._updates_by_scenario = {}   # learner thread only
        self._pending_group_batches = []  # hetero-shape extras (learner)
        self._last_update_fleets = ()
        self._updates_done = 0
        self._q: queue.Queue = queue.Queue(maxsize=queue_size)
        self._fanin = None
        self._stop = threading.Event()
        self._threads = []
        self._thread = None  # single-fleet back-compat handle
        self._actor_errors = [None] * max(1, self.num_fleets)
        self._env_steps_by_fleet = [0] * max(1, self.num_fleets)
        self._unhealthy_by_fleet = [0] * max(1, self.num_fleets)
        self._degraded_by_fleet = [False] * max(1, self.num_fleets)
        #: fleet re-admission (multi-fleet only): once the supervisor
        #: heals a dead fleet's pool, the learner restarts its actor
        #: thread so the fleet REJOINS the fan-in instead of staying
        #: zero-masked forever; the cooldown stops a hot respawn loop
        #: against a pool that immediately fails again
        self.fleet_restart_cooldown = 1.0
        self._fleet_restarts = [0] * max(1, self.num_fleets)
        self._fleet_restart_allowed = [0.0] * max(1, self.num_fleets)
        self._fleet_restart_steps = [0] * max(1, self.num_fleets)
        if hub is not None:
            if replay is not None and hasattr(replay, "register_with_hub"):
                replay.register_with_hub(hub)
            elif replay is not None:
                hub.register(
                    replay.name, counters=replay.counters,
                    timer=replay.timer, probe=replay.stats,
                )
            hub.register("actor_learner", probe=self.stats)
            # scenario plane components ride the same hub; counters are
            # deduplicated BY IDENTITY — sharing one EventCounters
            # across replay/randomizer/curriculum (the common setup)
            # must not fold the same events twice in the aggregate
            seen = {id(replay.counters)} if replay is not None else set()
            for name, comp in (
                ("scenario_randomizer", self.randomizer),
                ("scenario_curriculum", self.curriculum),
                ("ha_checkpointer", self.checkpointer),
            ):
                if comp is None:
                    continue
                dup = id(comp.counters) in seen
                seen.add(id(comp.counters))
                hub.register(
                    name,
                    counters=None if dup else comp.counters,
                    timer=comp.timer,
                    probe=comp.stats,
                )

    # -- aggregate views -----------------------------------------------------

    def stats(self):
        """Live training-loop accounting, readable mid-run (also the
        hub probe): fleet/step totals plus — with the scenario plane
        attached — per-scenario env-step and update counts, the
        current mix and assignments, so a curriculum shift is visible
        from the training loop alone (docs/scenarios.md)."""
        out = {
            "env_steps": self._env_steps,
            "unhealthy_env_steps": self._unhealthy_env_steps,
            "env_steps_by_fleet": list(self._env_steps_by_fleet),
            "updates": self._updates_done,
            "fleet_restarts": list(self._fleet_restarts),
            "dead_fleets": [
                fid for fid, e in enumerate(self._actor_errors)
                if e is not None
            ],
        }
        if self.randomizer is not None or self.curriculum is not None:
            merged = {}
            for d in self._scenario_steps_by_fleet:
                for sid, n in list(d.items()):
                    merged[sid] = merged.get(sid, 0) + n
            out["env_steps_by_scenario"] = merged
            out["updates_by_scenario"] = dict(self._updates_by_scenario)
            if self.randomizer is not None:
                out["scenario_assignments"] = self.randomizer.assignments
            if self.curriculum is not None:
                out["scenario_mix"] = self.curriculum.mix()
        return out

    # -- learner failover (blendjax.ha; docs/fault_tolerance.md) -------------

    def checkpoint_state(self):
        """The learner-side scalars one coordinated checkpoint records
        next to the TrainState: update counter, seed, the last
        published bus version, curriculum state and scenario
        assignments.  Everything JSON-able — it rides inline in the
        :class:`~blendjax.ha.checkpoint.TrainCheckpointer` manifest."""
        aux = {
            "updates": self._updates_done,
            "seed": self._seed,
            "last_published_version": self.last_published_version,
        }
        if self.curriculum is not None:
            aux["curriculum"] = self.curriculum.state_dict()
        if self.randomizer is not None:
            aux["scenario_assignments"] = self.randomizer.assignments
        return aux

    def load_checkpoint_state(self, state, aux):
        """Apply a restored TrainState + :meth:`checkpoint_state` dict:
        the update counter continues from the cut (the weight bus's
        ``step`` stamps and the checkpoint cadence both key off it),
        the actors' sampling snapshot is rebuilt from the restored
        params, the curriculum resumes mid-interval, and — when the
        scenario plane is attached — the checkpointed per-fleet
        assignment is re-pushed into the producers over the existing
        :meth:`~blendjax.scenario.randomize.DomainRandomizer.
        apply_assignment` path (the respawned learner's fleets must
        not keep serving the default scene)."""
        self.state = state
        if self._actor_device is not None:
            self._actor_params = jax.tree.map(
                jnp.asarray, jax.device_get(state.params)
            )
        else:
            self._actor_params = state.params
        self._updates_done = int(aux.get("updates", 0))
        self.last_published_version = aux.get("last_published_version")
        seed = aux.get("seed")
        if seed is not None and int(seed) != self._seed:
            # the manifest's seed is authoritative: the actor rollout
            # RNG folds in self._seed at thread start, so keeping a
            # mismatched constructor seed would silently diverge the
            # action-sampling stream from the checkpointed run
            log.warning(
                "restoring checkpoint cut under seed %d into a learner "
                "constructed with seed %d; adopting the checkpoint's "
                "seed so the actor sampling streams continue the "
                "checkpointed run", int(seed), self._seed,
            )
            self._seed = int(seed)
        if self.curriculum is not None and aux.get("curriculum"):
            self.curriculum.load_state_dict(aux["curriculum"])
        assignment = aux.get("scenario_assignments")
        if self.randomizer is not None and assignment:
            self.randomizer.apply_assignment(list(assignment))

    @property
    def _env_steps(self):
        return sum(self._env_steps_by_fleet)

    @property
    def _unhealthy_env_steps(self):
        return sum(self._unhealthy_by_fleet)

    @property
    def _actor_error(self):
        return next((e for e in self._actor_errors if e is not None), None)

    def _publish_params(self):
        """Swap the actors' sampling snapshot (atomic CPython attribute
        assignment).  Under a mesh the snapshot is gathered off the mesh
        onto uncommitted default-device arrays — per-env-step SPMD
        dispatch over the whole mesh (or committed-device dispatch)
        would dwarf the tiny policy's compute; see the constructor.

        Called once per completed update, which also makes it the
        weight-bus publication point: every ``publish_every``-th update
        snapshots the params onto the bus (host-gathered — the same
        gather the mesh actor path already pays), closing the
        learner -> serve-tier loop (docs/weight_bus.md)."""
        host = None
        if self._actor_device is not None:
            host = jax.device_get(self.state.params)
            self._actor_params = jax.tree.map(jnp.asarray, host)
        else:
            self._actor_params = self.state.params
        self._updates_done += 1
        if self.weight_bus is not None \
                and self._updates_done % self.publish_every == 0:
            try:
                self.last_published_version = self.weight_bus.publish(
                    # reuse the mesh path's host gather; single-device
                    # params gather here (the only transfer they pay)
                    host if host is not None
                    else jax.device_get(self.state.params),
                    step=self._updates_done,
                )
            except Exception:  # noqa: BLE001 - training outlives the bus
                log.exception("weight bus publish failed (training "
                              "continues; the serve tier keeps its "
                              "last good version)")
        if self.checkpointer is not None:
            # once per completed update (on- AND off-policy — whatever
            # advanced the params), same as the bus: the checkpointer
            # decides cadence itself and never raises into the loop
            self.checkpointer.maybe_checkpoint(self)

    # -- actor side ----------------------------------------------------------

    def _enqueue_segment(self, fid, seg_lists):
        """Hand a finished segment to the learner (bounded put,
        re-checked against stop).  Returns False once stop is set."""
        if self._fanin is not None:
            return self._fanin.put_segment(fid, seg_lists, self._stop)
        seg = tuple(np.stack(col) for col in seg_lists)
        while not self._stop.is_set():
            try:
                self._q.put(seg, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _actor(self, fid, pool):
        try:
            # derived from the constructor seed, distinct per fleet:
            # runs are reproducible, fleets decorrelated
            rng = jax.random.fold_in(
                jax.random.PRNGKey(self._seed), 0xAC708 + fid
            )
            obs, _ = pool.reset()
            obs = np.asarray(obs, np.float32)
            if obs.ndim == 1:
                obs = obs[:, None]
            pending_seg = None  # finished segment owed to the learner
            while not self._stop.is_set():
                seg_obs, seg_act, seg_rew, seg_done = [], [], [], []
                params = self._actor_params  # snapshot for whole segment
                for _ in range(self.rollout_len):
                    action, _logp, rng = self._sample(params, rng, obs)
                    action = np.asarray(action)
                    if self.pipeline:
                        # double-buffer: submit first, so the fleet
                        # simulates frame t+1 while this thread finalizes
                        # segment t (the stack + queue handoff below can
                        # even block on a full queue — the envs keep
                        # integrating physics through the stall)
                        pool.step_async(self.action_map(action))
                        if pending_seg is not None:
                            if not self._enqueue_segment(fid, pending_seg):
                                # stop arrived with a batch in flight:
                                # drain it so the pool is reusable for
                                # lock-step callers after run() returns
                                pool.step_wait()
                                return
                            pending_seg = None
                        nobs, rew, done, infos = pool.step_wait_full()
                    else:
                        nobs, rew, done, infos = pool.step(
                            self.action_map(action)
                        )
                    # degraded-mode accounting: quarantined slots return
                    # synthetic zero-reward transitions (see
                    # docs/fault_tolerance.md) — surface how much of the
                    # rollout they make up instead of absorbing it silently
                    unhealthy = sum(
                        1 for inf in infos if not inf.get("healthy", True)
                    )
                    if unhealthy:
                        self._unhealthy_by_fleet[fid] += unhealthy
                        if not self._degraded_by_fleet[fid]:
                            self._degraded_by_fleet[fid] = True
                            log.warning(
                                "actor rollout degraded (fleet %d): %d/%d "
                                "envs quarantined (synthetic transitions "
                                "in the batch)", fid, unhealthy,
                                pool.num_envs,
                            )
                    elif self._degraded_by_fleet[fid]:
                        self._degraded_by_fleet[fid] = False
                        log.warning(
                            "actor rollout healthy again (fleet %d)", fid
                        )
                    scen = None
                    if self.randomizer is not None:
                        # scenario attribution (docs/scenarios.md): the
                        # producer's in-band echo wins; a synthetic /
                        # pre-push transition falls back to the fleet's
                        # assignment.  A re-admitted env gets the
                        # fleet's scenario re-pushed over a fresh
                        # channel — the respawned producer must not
                        # keep serving the default scene.
                        assigned = self.randomizer.scenario_of(fid)
                        steps = self._scenario_steps_by_fleet[fid]
                        scen = []
                        for i, inf in enumerate(infos):
                            sid = inf.get("scenario") or assigned
                            scen.append(sid)
                            if sid is not None:
                                steps[sid] = steps.get(sid, 0) + 1
                            if inf.get("readmitted"):
                                self.randomizer.reassign(fid, i)
                            self.randomizer.note_info(fid, inf)
                        self.randomizer.maybe_resample(fid)
                    seg_obs.append(obs)
                    seg_act.append(action)
                    seg_rew.append(np.asarray(rew, np.float32))
                    seg_done.append(np.asarray(done, bool))
                    prev_obs = obs
                    obs = np.asarray(nobs, np.float32)
                    if obs.ndim == 1:
                        obs = obs[:, None]
                    if self.replay is not None:
                        # quarantine-aware appends: a synthetic transition
                        # from a quarantined slot is stored flagged and
                        # never sampled (docs/replay.md)
                        self.replay.extend(
                            (
                                {
                                    "obs": prev_obs[i],
                                    "action": action[i],
                                    "reward": seg_rew[-1][i],
                                    "next_obs": obs[i],
                                    "done": seg_done[-1][i],
                                }
                                for i in range(pool.num_envs)
                            ),
                            healthy=[
                                inf.get("healthy", True) for inf in infos
                            ],
                            # scenario stamps ride in-band into the
                            # per-scenario replay strata (None when the
                            # plane is off: appends are byte-identical)
                            scenarios=scen,
                        )
                    self._env_steps_by_fleet[fid] += pool.num_envs
                seg_lists = (seg_obs, seg_act, seg_rew, seg_done)
                if self.pipeline:
                    # deferred into the next submission's simulation window
                    pending_seg = seg_lists
                else:
                    if not self._enqueue_segment(fid, seg_lists):
                        return
        except BaseException as exc:  # noqa: BLE001 - surfaced by learner
            self._actor_errors[fid] = exc
            if len(self.pools) <= 1:
                self._stop.set()
            else:
                # multi-fleet: the OTHER fleets keep training — the
                # fan-in zero-masks this fleet's rows from here on
                flight_recorder.note(
                    "fleet_actor_failed", target=f"fleet{fid}",
                    error=f"{type(exc).__name__}: {exc}",
                )
                log.warning(
                    "actor fleet %d failed (%s: %s); remaining fleets "
                    "continue", fid, type(exc).__name__, exc,
                )

    # -- learner side --------------------------------------------------------

    def _adopt_pipeline_params(self):
        """Mirror the stage fleet's assembled params into the learner's
        TrainState (and the actors' sampling snapshot)."""
        params = jax.tree.map(
            jnp.asarray, self.pipeline_driver.gather_params()
        )
        self.state = self.state._replace(
            params=params, step=self.pipeline_driver.updates_done,
        )
        self._actor_params = params

    def _pipeline_replay_update(self, obs, action, reward, is_weight):
        """One off-policy update through the MPMD stage fleet: the
        advantage is batch-normalized HERE over the full batch (so the
        per-microbatch loss means average to ``replay_loss_fn``), the
        microbatched records stream through the pipeline, and the
        committed params come back as the new actor/bus/checkpoint
        mirror."""
        r = np.asarray(reward, np.float64)
        adv = ((r - r.mean()) / (r.std() + 1e-6)).astype(np.float32)
        loss = self.pipeline_driver.update(
            np.asarray(obs, np.float32),
            {
                "action": np.asarray(action),
                "adv": adv,
                "w": np.asarray(is_weight, np.float32),
            },
            self.pipeline_microbatches,
        )
        self._adopt_pipeline_params()
        return loss

    def _replay_step_and_refresh(self, batch, idx, reward):
        """The shared off-policy post-draw block (online tail AND
        run_offline): one sampled update, actor params mirror, and the
        sampled rows' priorities refreshed from |advantage| under the
        batch baseline (the same signal the loss weights)."""
        if self.pipeline_driver is not None:
            loss = self._pipeline_replay_update(
                batch["obs"], batch["action"], batch["reward"],
                batch["is_weight"],
            )
        else:
            self.state, loss = self._replay_step(self.state, batch)
        self._publish_params()
        r = np.asarray(reward, np.float64)
        self.replay.update_priorities(idx, np.abs(r - r.mean()))
        return float(loss)

    def _replay_update(self, data, idx, weights):
        """One off-policy update from a host-side sampled batch, placed
        with the same batch-axis sharding as the rollout batches."""
        from blendjax.btt.prefetch import put_batch

        batch = put_batch(
            {
                "obs": data["obs"],
                "action": data["action"],
                "reward": data["reward"],
                "is_weight": weights,
            },
            self._batch_sharding,
        )
        return self._replay_step_and_refresh(batch, idx, data["reward"])

    def _drain_replay_ratio(self, replay_losses):
        """The learner's off-policy tail: up to ``replay_ratio`` sampled
        updates, skipped (not blocked on) while the buffer is short —
        early in training the on-policy path must keep moving.
        ``timeout=0`` makes the shortfall check and the draw one atomic
        step (a pre-check of ``num_eligible`` could pass and then a
        degraded fleet's unhealthy appends evict the eligible rows
        before the draw acquired the lock, blocking the learner)."""
        for _ in range(self.replay_ratio):
            try:
                data, idx, w = self.replay.sample(
                    self.replay_batch, timeout=0.0,
                    keys=("obs", "action", "reward"),
                )
            except TimeoutError:
                # underfilled buffer OR (sharded) a storage outage the
                # quarantine could not route around — skip, keep the
                # on-policy path moving, leave a countable trace
                self.replay.counters.incr("replay_sample_skips")
                return
            replay_losses.append(self._replay_update(data, idx, w))

    def run_offline(self, num_updates, batch_size=64, *, arena_pool=None,
                    prefetch=2):
        """Train purely from the replay buffer — zero Blender processes
        (e.g. after :func:`blendjax.replay.prefill_from_btr`).

        Sampled batches are gathered straight into recycled
        :class:`~blendjax.btt.arena.ArenaPool` buffers and staged onto
        the device through ``device_prefetch`` — the PR-1 feed seam,
        driven by the sampler instead of the wire; sampling for batch
        t+1 overlaps the update on batch t.  Under ``mesh=`` the batches
        land pre-sharded ``P('data')`` exactly like the online paths.
        Returns a stats dict.
        """
        from blendjax.btt.arena import ArenaPool
        from blendjax.btt.prefetch import device_prefetch

        if self.replay is None:
            raise RuntimeError("run_offline requires a replay buffer")
        if self.mesh is not None and batch_size % self._data_size:
            raise ValueError(
                f"batch_size={batch_size} does not divide over the "
                f"mesh's data axis ({self._data_size} shards); pick "
                "batch sizes divisible by the mesh axes they shard over"
            )
        pool = arena_pool or ArenaPool(pool_size=prefetch + 2)
        stop = threading.Event()
        gen = self.replay.sample_batches(
            batch_size, arena_pool=pool, stop_event=stop,
            # gather (and transfer) only what the off-policy loss and
            # the priority refresh read — next_obs/done alone would
            # double the per-batch copy volume for image observations
            keys=("obs", "action", "reward"),
        )
        losses = []
        t0 = time.perf_counter()
        if self.pipeline_driver is not None:
            # MPMD pipeline mode: stage 0 consumes the sampler's arena
            # batches DIRECTLY — no device staging hop; the pipeline
            # itself is the device.  The driver's bounded in-flight
            # window composes with the bounded ArenaPool as
            # backpressure: a full pipeline parks the feed
            # (``pipe_feed_parks``), the parked feed keeps the arena
            # buffer checked out, and the sampler blocks on ``acquire``
            # instead of allocating.  Each buffer recycles the moment
            # the update round has fully left it — the same
            # recycle-after-transfer contract ``device_prefetch`` keeps.
            try:
                for ab in gen:
                    data = ab.data
                    try:
                        losses.append(self._replay_step_and_refresh(
                            data,
                            np.asarray(data["replay_idx"]),
                            np.asarray(data["reward"]),
                        ))
                    finally:
                        ab.recycle()
                    if len(losses) >= num_updates:
                        break
            finally:
                stop.set()
                gen.close()
            elapsed = time.perf_counter() - t0
            return {
                "updates": len(losses),
                "updates_per_sec": round(len(losses) / elapsed, 2),
                "losses": losses,
                "replay": self.replay.stats(),
                "elapsed_s": round(elapsed, 3),
            }
        it = device_prefetch(
            gen, size=prefetch, sharding=self._batch_sharding,
            timer=self.replay.timer,
        )
        try:
            for dev_batch in it:
                # sidecar meta came back in-band (the prefetcher unwraps
                # ArenaBatch), keying the priority refresh
                losses.append(self._replay_step_and_refresh(
                    {
                        "obs": dev_batch["obs"],
                        "action": dev_batch["action"],
                        "reward": dev_batch["reward"],
                        "is_weight": dev_batch["is_weight"],
                    },
                    np.asarray(dev_batch["replay_idx"]),
                    np.asarray(dev_batch["reward"]),
                ))
                if len(losses) >= num_updates:
                    break
        finally:
            stop.set()
            it.close()
        elapsed = time.perf_counter() - t0
        return {
            "updates": len(losses),
            "updates_per_sec": round(len(losses) / elapsed, 2),
            "losses": losses,
            "replay": self.replay.stats(),
            "elapsed_s": round(elapsed, 3),
        }

    def _fleet_alive(self, fid):
        return (fid < len(self._threads)
                and self._threads[fid].is_alive())

    # -- scenario plane ------------------------------------------------------

    def _note_update_scenarios(self):
        """Attribute one completed on-policy update to the scenarios of
        its contributing fleets (learner thread only)."""
        if self.randomizer is None and self.curriculum is None:
            return
        fleets = self._last_update_fleets or tuple(
            range(max(1, len(self.pools)))
        )
        for fid in fleets:
            sid = (self.randomizer.scenario_of(fid)
                   if self.randomizer is not None else None)
            key = sid if sid is not None else "_unlabelled"
            self._updates_by_scenario[key] = \
                self._updates_by_scenario.get(key, 0) + 1

    def _tick_curriculum(self):
        """One curriculum tick per completed update: on its interval
        the scheduler reweights the mix from the replay strata, and a
        changed mix is driven through the randomizer as a fresh
        per-fleet assignment (docs/scenarios.md)."""
        if self.curriculum is None:
            return
        stats_fn = None
        if self.replay is not None \
                and hasattr(self.replay, "scenario_stats"):
            stats_fn = self.replay.scenario_stats
        mix = self.curriculum.tick(stats_fn)
        if mix is not None and self.randomizer is not None \
                and self.pools:
            assignment = self.curriculum.assign(len(self.pools))
            changed = self.randomizer.apply_assignment(assignment)
            if changed:
                log.info(
                    "curriculum reassigned fleets %s -> %s "
                    "(mix %s)", changed,
                    [assignment[f] for f in changed], mix,
                )

    def _maybe_restart_fleets(self):
        """Fleet re-admission: a fleet whose actor thread died (every
        env dead -> the pool raised) rejoins once the supervisor's heal
        path has the pool answering again — `dead_fleets` shrinks
        instead of zero-masking the fleet forever.  Single-fleet runs
        keep the legacy fail-fast contract (the error stops the run)."""
        if len(self.pools) <= 1 or self._stop.is_set():
            return
        now = time.monotonic()
        for fid, pool in enumerate(self.pools):
            if self._actor_errors[fid] is None or self._fleet_alive(fid):
                continue
            if now < self._fleet_restart_allowed[fid]:
                continue
            if (self._fleet_restarts[fid] > 0
                    and self._env_steps_by_fleet[fid]
                    <= self._fleet_restart_steps[fid]):
                # the previous restart died without stepping a single
                # env: the error is deterministic (bad action_map,
                # schema drift), not a pool death — restarting forever
                # would suppress it, so give up and leave the fleet in
                # dead_fleets with its real exception
                continue
            healthy = getattr(pool, "healthy", None)
            if healthy is not None and not np.asarray(healthy).any():
                continue  # still dead; the supervisor owns the respawn
            self._fleet_restart_allowed[fid] = (
                now + self.fleet_restart_cooldown
            )
            self._fleet_restart_steps[fid] = self._env_steps_by_fleet[fid]
            self._actor_errors[fid] = None
            self._fleet_restarts[fid] += 1
            t = threading.Thread(
                target=self._actor, args=(fid, pool), daemon=True,
                name=f"bjx-actor-{fid}.{self._fleet_restarts[fid]}",
            )
            self._threads[fid] = t
            flight_recorder.note(
                "fleet_restart", target=f"fleet{fid}",
                restart=self._fleet_restarts[fid],
            )
            log.warning(
                "fleet %d healed: restarting its actor thread "
                "(restart %d); the fleet rejoins the fan-in", fid,
                self._fleet_restarts[fid],
            )
            t.start()

    def _next_fanin_batch(self, deadline):
        """One pre-sharded global batch from the fan-in, or ``None`` on
        deadline/stop, or raises once EVERY fleet has failed.

        With a heterogeneous fleet set, one collect can yield SEVERAL
        shape groups (:meth:`SegmentFanIn.assemble_groups`): the first
        is returned now and the rest queue for subsequent calls, so
        every scenario's rows reach the learner.  ``fanin_min_ready``
        additionally lets the collect return before slow fleets
        contribute (their rows zero-masked this round)."""
        while True:
            if self._pending_group_batches:
                batch, seg_reward, fleets = \
                    self._pending_group_batches.pop(0)
                self._last_update_fleets = fleets
                return self._fanin.to_device(batch), seg_reward
            if deadline is not None and time.perf_counter() >= deadline:
                return None
            self._maybe_restart_fleets()
            if self._stop.is_set():
                # a single-fleet actor failure stops the run (legacy
                # contract): surface it instead of ending silently
                errs = [e for e in self._actor_errors if e is not None]
                if errs and len(errs) == len(self.pools):
                    raise RuntimeError(
                        "actor thread failed" if len(self.pools) == 1
                        else f"all {len(self.pools)} actor fleets failed"
                    ) from errs[0]
                return None
            mono_deadline = None
            if deadline is not None:
                mono_deadline = (
                    time.monotonic() + deadline - time.perf_counter()
                )
            segs = self._fanin.collect(
                self._fleet_alive, self._stop, deadline=mono_deadline,
                min_ready=self.fanin_min_ready,
            )
            if deadline is not None and time.perf_counter() >= deadline:
                self._fanin.recycle_segments(segs)
                return None
            if segs:
                if self.curriculum is not None \
                        and self.randomizer is not None:
                    # per-scenario return evidence for the curriculum
                    for f, s in segs.items():
                        self.curriculum.observe_return(
                            self.randomizer.scenario_of(f),
                            float(s.data["rewards"].mean()),
                        )
                rewards = {
                    f: (float(s.data["rewards"].sum()),
                        s.data["rewards"].size)
                    for f, s in segs.items()
                }
                queued = []
                for gid, group in self._fanin.split_groups(segs):
                    batch = self._fanin.assemble(
                        group, stop_event=self._stop, _group=gid,
                    )
                    if batch is None:
                        for b, _, _ in queued:
                            b.recycle()
                        return None
                    rsum = sum(rewards[f][0] for f in group)
                    rn = sum(rewards[f][1] for f in group)
                    queued.append(
                        (batch, rsum / max(rn, 1), tuple(group))
                    )
                self._pending_group_batches.extend(queued)
                continue
            if all(not self._fleet_alive(f)
                   for f in range(len(self.pools))):
                errs = [e for e in self._actor_errors if e is not None]
                if errs:
                    raise RuntimeError(
                        f"all {len(self.pools)} actor fleets failed"
                    ) from errs[0]
                return None

    def run(self, num_updates=None, seconds=None):
        """Run the overlapped loop for ``num_updates`` learner steps OR a
        ``seconds`` wall-clock budget (whichever is given; both = either
        limit ends the run); returns a stats dict.

        Re-runnable: each call gets a fresh stop event, a zeroed step
        counter, and an emptied queue (a previous run's buffered segments
        carry a stale policy and would also corrupt the throughput math).
        """
        if not self.pools:
            # constructible fleet-less for the pure off-policy path
            # (prefilled replay buffer): that path is run_offline()
            raise RuntimeError(
                "no EnvPool attached; use run_offline() to train from "
                "the replay buffer"
            )
        if num_updates is None and seconds is None:
            raise ValueError("pass num_updates and/or seconds")
        if any(t.is_alive() for t in self._threads):
            # a leaked actor (previous run's join timed out on a stalled
            # RPC) sharing the REQ sockets with a fresh one would corrupt
            # the zmq protocol and double-count env steps
            raise RuntimeError(
                "previous run's actor thread is still alive; close the "
                "pool or wait before re-running"
            )
        self._stop = threading.Event()
        self._actor_errors = [None] * len(self.pools)
        self._env_steps_by_fleet = [0] * len(self.pools)
        self._unhealthy_by_fleet = [0] * len(self.pools)
        self._degraded_by_fleet = [False] * len(self.pools)
        self._fleet_restarts = [0] * len(self.pools)
        self._fleet_restart_allowed = [0.0] * len(self.pools)
        self._fleet_restart_steps = [0] * len(self.pools)
        self._scenario_steps_by_fleet = [
            dict() for _ in range(len(self.pools))
        ]
        self._updates_by_scenario = {}
        for b, _, _ in self._pending_group_batches:
            b.recycle()  # a previous run's stale-policy leftovers
        self._pending_group_batches = []
        self._last_update_fleets = ()
        if self.randomizer is not None and self.curriculum is not None \
                and not any(
                    s is not None for s in self.randomizer.assignments
                ):
            # bootstrap: never-assigned fleets get the curriculum's
            # starting mix before the first rollout, so scenario labels
            # exist from the first transition
            self.randomizer.apply_assignment(
                self.curriculum.assign(len(self.pools))
            )
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        if self._use_fanin:
            from blendjax.parallel.podracer import SegmentFanIn

            # fresh fan-in per run: empty queues, recycled arenas
            self._fanin = SegmentFanIn(
                [p.num_envs for p in self.pools],
                mesh=self.mesh,
                queue_size=self.queue_size,
            )
        self._threads = [
            threading.Thread(
                target=self._actor, args=(fid, p), daemon=True,
                name=f"bjx-actor-{fid}",
            )
            for fid, p in enumerate(self.pools)
        ]
        self._thread = self._threads[0]  # back-compat handle
        t0 = time.perf_counter()
        deadline = t0 + seconds if seconds is not None else None
        for t in self._threads:
            t.start()
        losses, seg_rewards, replay_losses = [], [], []
        try:
            while True:
                if num_updates is not None and len(losses) >= num_updates:
                    break
                if deadline is not None and time.perf_counter() >= deadline:
                    break
                if self._fanin is not None:
                    got = self._next_fanin_batch(deadline)
                    if got is None:
                        break
                    batch, seg_reward = got
                else:
                    while True:
                        if self._actor_error is not None:
                            raise RuntimeError(
                                "actor thread failed"
                            ) from self._actor_error
                        try:
                            seg = self._q.get(timeout=0.5)
                            break
                        except queue.Empty:
                            if (deadline is not None
                                    and time.perf_counter() >= deadline):
                                seg = None
                                break
                    if seg is None:
                        break
                    batch = jax.device_put(
                        {"obs": seg[0], "actions": seg[1],
                         "rewards": seg[2], "dones": seg[3]}
                    )
                    seg_reward = float(seg[2].mean())
                self.state, loss = self._step(self.state, batch)
                self._publish_params()
                losses.append(float(loss))
                seg_rewards.append(seg_reward)
                self._note_update_scenarios()
                self._tick_curriculum()
                if self.replay is not None and self.replay_ratio > 0:
                    self._drain_replay_ratio(replay_losses)
        finally:
            self._stop.set()
            for t in self._threads:
                t.join(timeout=10)
        elapsed = time.perf_counter() - t0
        stats = {
            "updates": len(losses),
            "env_steps": self._env_steps,
            "unhealthy_env_steps": self._unhealthy_env_steps,
            "env_steps_per_sec": round(self._env_steps / elapsed, 1),
            "updates_per_sec": round(len(losses) / elapsed, 2),
            "first_segment_reward": seg_rewards[0] if seg_rewards else None,
            "last_segment_reward": seg_rewards[-1] if seg_rewards else None,
            "segment_rewards": seg_rewards,
            "losses": losses,
            "elapsed_s": round(elapsed, 3),
        }
        if len(self.pools) > 1 or self.mesh is not None:
            stats["num_fleets"] = len(self.pools)
            stats["env_steps_by_fleet"] = list(self._env_steps_by_fleet)
            stats["dead_fleets"] = [
                fid for fid, e in enumerate(self._actor_errors)
                if e is not None
            ]
            stats["fleet_restarts"] = list(self._fleet_restarts)
            stats["sharded"] = self.mesh is not None
        if self.replay is not None:
            stats["replay_updates"] = len(replay_losses)
            stats["replay_losses"] = replay_losses
            stats["replay"] = self.replay.stats()
        if self.randomizer is not None or self.curriculum is not None:
            live = self.stats()
            stats["env_steps_by_scenario"] = \
                live.get("env_steps_by_scenario", {})
            stats["updates_by_scenario"] = \
                live.get("updates_by_scenario", {})
            if "scenario_mix" in live:
                stats["scenario_mix"] = live["scenario_mix"]
            if "scenario_assignments" in live:
                stats["scenario_assignments"] = \
                    live["scenario_assignments"]
            if self.curriculum is not None:
                stats["curriculum"] = self.curriculum.stats()
        return stats
