"""Policies + REINFORCE losses for the control (cartpole) workload.

The reference's control example uses a hand-written P-controller
(``examples/control/cartpole.py:19-35``) and leaves learning to the user;
blendjax ships a small learnable stack: an MLP policy (categorical over
discrete actions or Gaussian over continuous ones) with a jitted REINFORCE
update, designed to train against a batched :class:`blendjax.btt.envpool.EnvPool`
under a data-parallel mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from blendjax.models.layers import dense_apply, dense_init


def init(key, obs_dim, num_actions, hidden=(64, 64), continuous=False):
    """MLP policy params.  ``continuous=True`` adds a state-independent
    log-std head for a Gaussian policy."""
    dims = (obs_dim, *hidden)
    keys = jax.random.split(key, len(dims))
    params = {
        "layers": [
            dense_init(keys[i], dims[i], dims[i + 1]) for i in range(len(dims) - 1)
        ],
        "out": dense_init(keys[-1], dims[-1], num_actions),
    }
    if continuous:
        params["log_std"] = jnp.zeros((num_actions,))
    return params


def _dense_mq(p, x):
    """``dense_apply`` accepting either a float ``{'w', 'b'}`` or an
    int8 ``{'w_q', 'w_scale', 'b'}`` layer
    (:func:`blendjax.ops.quant.quantize_policy`) — one ``logits`` body
    serves both precisions, like the seqformer's dispatch."""
    if "w_q" in p:
        from blendjax.ops.quant import dense_apply_int8

        return dense_apply_int8(p, x)
    return dense_apply(p, x)


def logits(params, obs):
    x = jnp.asarray(obs, jnp.float32)
    for layer in params["layers"]:
        x = jnp.tanh(_dense_mq(layer, x))
    return _dense_mq(params["out"], x)


def sample_action(params, key, obs):
    """Sample actions (and their log-probs) for a batch of observations."""
    out = logits(params, obs)
    if "log_std" in params:
        std = jnp.exp(params["log_std"])
        eps = jax.random.normal(key, out.shape)
        action = out + std * eps
        logp = gaussian_log_prob(params, obs, action)
        return action, logp
    action = jax.random.categorical(key, out, axis=-1)
    logp = jax.nn.log_softmax(out)[jnp.arange(out.shape[0]), action]
    return action, logp


def categorical_log_prob(params, obs, actions):
    lp = jax.nn.log_softmax(logits(params, obs))
    return jnp.take_along_axis(lp, actions[..., None].astype(jnp.int32), axis=-1)[..., 0]


def gaussian_log_prob(params, obs, actions):
    mean = logits(params, obs)
    std = jnp.exp(params["log_std"])
    z = (actions - mean) / std
    return (-0.5 * z * z - params["log_std"] - 0.5 * jnp.log(2 * jnp.pi)).sum(-1)


def discounted_returns(rewards, dones, gamma=0.99):
    """Per-step discounted returns over a (T, N) rollout, resetting at
    episode boundaries.  ``lax.scan`` keeps it jittable for any T."""

    def step(carry, inp):
        r, d = inp
        carry = r + gamma * carry * (1.0 - d)
        return carry, carry

    _, rev = jax.lax.scan(
        step,
        jnp.zeros(rewards.shape[1]),
        (rewards[::-1], dones[::-1].astype(jnp.float32)),
    )
    return rev[::-1]


def reinforce_loss(params, obs, actions, returns, continuous=False):
    """-E[log pi(a|s) * (G - baseline)] with a batch-mean baseline.

    ``obs`` (T*N, obs_dim), ``actions`` (T*N,), ``returns`` (T*N,).
    """
    if continuous:
        logp = gaussian_log_prob(params, obs, actions)
    else:
        logp = categorical_log_prob(params, obs, actions)
    advantage = returns - returns.mean()
    advantage = advantage / (returns.std() + 1e-6)
    return -jnp.mean(logp * jax.lax.stop_gradient(advantage))


def value_init(key, obs_dim, hidden=(64, 64)):
    """MLP state-value params (critic): the policy trunk with a
    1-output head — one source of truth for the architecture."""
    return init(key, obs_dim, 1, hidden=hidden)


def value_apply(params, obs):
    return logits(params, obs)[..., 0]


def gae(rewards, values, last_values, dones, gamma=0.99, lam=0.95):
    """Generalized advantage estimation over a (T, N) rollout.

    ``values`` (T, N) are V(s_t) along the rollout, ``last_values`` (N,)
    is V(s_T) bootstrapping the tail; episode boundaries cut both the
    bootstrap and the trace.  Returns (advantages, value_targets), each
    (T, N); jittable via ``lax.scan``."""
    nd = 1.0 - dones.astype(jnp.float32)
    next_values = jnp.concatenate([values[1:], last_values[None]], axis=0)
    deltas = rewards + gamma * next_values * nd - values

    def step(carry, inp):
        delta, mask = inp
        carry = delta + gamma * lam * mask * carry
        return carry, carry

    _, rev = jax.lax.scan(
        step, jnp.zeros(rewards.shape[1]), (deltas[::-1], nd[::-1])
    )
    adv = rev[::-1]
    return adv, adv + values


def ppo_loss(actor, critic, batch, clip_eps=0.2, vf_coef=0.5,
             ent_coef=0.01, continuous=False):
    """Clipped-surrogate PPO objective + value MSE + entropy bonus.

    ``batch``: obs (B, D), actions (B,), logp_old (B,), advantages (B,)
    (normalized here), targets (B,), optional mask (B,) — zero weight
    for fabricated transitions (an autoresetting pool's reset step
    records a sampled-but-never-executed action; see
    ``examples/control/train_ppo.py``).  Returns the combined scalar.
    """
    obs, actions = batch["obs"], batch["actions"]
    w = batch.get("mask")
    if w is None:
        w = jnp.ones(actions.shape[0], jnp.float32)
    wsum = jnp.maximum(w.sum(), 1.0)

    def wmean(x):
        return (w * x).sum() / wsum

    if continuous:
        logp = gaussian_log_prob(actor, obs, actions)
    else:
        logp = categorical_log_prob(actor, obs, actions)
    adv = batch["advantages"]
    mu = wmean(adv)
    std = jnp.sqrt(wmean((adv - mu) ** 2))
    adv = (adv - mu) / (std + 1e-6)
    ratio = jnp.exp(logp - batch["logp_old"])
    clipped = jnp.clip(ratio, 1.0 - clip_eps, 1.0 + clip_eps)
    policy_loss = -wmean(jnp.minimum(ratio * adv, clipped * adv))
    v = value_apply(critic, obs)
    value_loss = wmean((v - batch["targets"]) ** 2)
    if continuous:
        ent = jnp.sum(actor["log_std"] + 0.5 * jnp.log(2 * jnp.pi * jnp.e))
    else:
        lp = jax.nn.log_softmax(logits(actor, obs))
        ent = -wmean(jnp.sum(jnp.exp(lp) * lp, axis=-1))
    return policy_loss + vf_coef * value_loss - ent_coef * ent
