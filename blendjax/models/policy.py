"""Policies + REINFORCE losses for the control (cartpole) workload.

The reference's control example uses a hand-written P-controller
(``examples/control/cartpole.py:19-35``) and leaves learning to the user;
blendjax ships a small learnable stack: an MLP policy (categorical over
discrete actions or Gaussian over continuous ones) with a jitted REINFORCE
update, designed to train against a batched :class:`blendjax.btt.envpool.EnvPool`
under a data-parallel mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from blendjax.models.layers import dense_apply, dense_init


def init(key, obs_dim, num_actions, hidden=(64, 64), continuous=False):
    """MLP policy params.  ``continuous=True`` adds a state-independent
    log-std head for a Gaussian policy."""
    dims = (obs_dim, *hidden)
    keys = jax.random.split(key, len(dims))
    params = {
        "layers": [
            dense_init(keys[i], dims[i], dims[i + 1]) for i in range(len(dims) - 1)
        ],
        "out": dense_init(keys[-1], dims[-1], num_actions),
    }
    if continuous:
        params["log_std"] = jnp.zeros((num_actions,))
    return params


def logits(params, obs):
    x = jnp.asarray(obs, jnp.float32)
    for layer in params["layers"]:
        x = jnp.tanh(dense_apply(layer, x))
    return dense_apply(params["out"], x)


def sample_action(params, key, obs):
    """Sample actions (and their log-probs) for a batch of observations."""
    out = logits(params, obs)
    if "log_std" in params:
        std = jnp.exp(params["log_std"])
        eps = jax.random.normal(key, out.shape)
        action = out + std * eps
        logp = gaussian_log_prob(params, obs, action)
        return action, logp
    action = jax.random.categorical(key, out, axis=-1)
    logp = jax.nn.log_softmax(out)[jnp.arange(out.shape[0]), action]
    return action, logp


def categorical_log_prob(params, obs, actions):
    lp = jax.nn.log_softmax(logits(params, obs))
    return jnp.take_along_axis(lp, actions[..., None].astype(jnp.int32), axis=-1)[..., 0]


def gaussian_log_prob(params, obs, actions):
    mean = logits(params, obs)
    std = jnp.exp(params["log_std"])
    z = (actions - mean) / std
    return (-0.5 * z * z - params["log_std"] - 0.5 * jnp.log(2 * jnp.pi)).sum(-1)


def discounted_returns(rewards, dones, gamma=0.99):
    """Per-step discounted returns over a (T, N) rollout, resetting at
    episode boundaries.  ``lax.scan`` keeps it jittable for any T."""

    def step(carry, inp):
        r, d = inp
        carry = r + gamma * carry * (1.0 - d)
        return carry, carry

    _, rev = jax.lax.scan(
        step,
        jnp.zeros(rewards.shape[1]),
        (rewards[::-1], dones[::-1].astype(jnp.float32)),
    )
    return rev[::-1]


def reinforce_loss(params, obs, actions, returns, continuous=False):
    """-E[log pi(a|s) * (G - baseline)] with a batch-mean baseline.

    ``obs`` (T*N, obs_dim), ``actions`` (T*N,), ``returns`` (T*N,).
    """
    if continuous:
        logp = gaussian_log_prob(params, obs, actions)
    else:
        logp = categorical_log_prob(params, obs, actions)
    advantage = returns - returns.mean()
    advantage = advantage / (returns.std() + 1e-6)
    return -jnp.mean(logp * jax.lax.stop_gradient(advantage))
