"""SeqFormer — temporal transformer over streamed Blender episodes.

The reference has no sequence models (SURVEY.md §5: long-context "absent");
blendjax's episodes — frames, observations, actions streamed out of
Blender — are sequences, and this is the flagship long-context model over
them: a causal transformer world-model that consumes an episode's
observation sequence and predicts the next observation at every step
(the standard self-supervised objective for learned simulators).

TPU-first design decisions:

- plain ``{name: array}`` pytrees (jit/shard/donate-clean, like every
  blendjax model);
- bfloat16 compute on the MXU, float32 params and softmax/layernorm
  accumulation;
- **pluggable attention**: ``apply(..., attn_fn=...)`` accepts any
  ``(q, k, v) -> out`` — pass
  :func:`blendjax.parallel.make_ring_attention` output to run the sequence
  axis sharded over the mesh (ring or Ulysses), nothing to change in the
  model;
- optional **mixture-of-experts MLP** (``n_experts > 0``): expert weights
  stack on a leading axis that shards over an ``'expert'`` mesh axis.
  Two apply-time evaluation modes over the SAME parameters:
  ``moe_impl='dense'`` (soft mixture, every expert evaluated, gate-
  weighted psum over the expert shards) and ``moe_impl='topk'`` (routed
  expert parallelism — top-k gating with capacity factor, static-shaped
  GShard-style dispatch, dropped tokens ride the residual; see
  :mod:`blendjax.models.moe`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from blendjax.models.layers import (
    apply_rope,
    apply_rope_rows,
    dense_apply,
    dense_init,
    gelu,
    rope_table,
)
from blendjax.ops.quant import maybe_quantized_einsum
from blendjax.parallel.ring_attention import full_attention


def _dense_mq(p, x, dtype):
    """``dense_apply`` accepting either a float ``{'w', 'b'}`` or an
    int8 ``{'w_q', 'w_scale', 'b'}`` weight dict
    (:func:`blendjax.ops.quant.quantize_seqformer`)."""
    if "w_q" not in p:
        return dense_apply(p, x, dtype=dtype)
    out = maybe_quantized_einsum("...d,df->...f", x, p, dtype)
    return (out + p["b"]).astype(dtype)


def _proj_mq(p, x, eq, dtype):
    """Head-major attention projection with the same float/int8
    dispatch; bias included."""
    out = maybe_quantized_einsum(eq, x, p, dtype)
    b = p["b"].astype(dtype if "w_q" not in p else jnp.float32)
    return (out + b).astype(dtype)


def _wq_head_dim(params):
    wq = params["blocks"][0]["wq"]
    return (wq["w"] if "w" in wq else wq["w_q"]).shape[-1]


def _ln_init(d):
    return {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))}


def _ln_apply(p, x):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    out = (x32 - mu) * jax.lax.rsqrt(var + 1e-6)
    return (out * p["scale"] + p["bias"]).astype(x.dtype)


def _moe_init(key, n_experts, d, d_ff):
    kg, k1, k2 = jax.random.split(key, 3)
    s1 = jnp.sqrt(2.0 / d)
    s2 = jnp.sqrt(2.0 / d_ff)
    return {
        "gate": dense_init(kg, d, n_experts),
        "w1": jax.random.normal(k1, (n_experts, d, d_ff)) * s1,
        "b1": jnp.zeros((n_experts, d_ff)),
        "w2": jax.random.normal(k2, (n_experts, d_ff, d)) * s2,
        "b2": jnp.zeros((n_experts, d)),
    }


def _moe_apply(p, x, dtype):
    """Soft mixture over all experts (static shapes, expert-sharded psum)."""
    gates = jax.nn.softmax(dense_apply(p["gate"], x, dtype=jnp.float32), axis=-1)
    h = jnp.einsum("btd,edf->betf", x.astype(dtype), p["w1"].astype(dtype))
    h = gelu(h + p["b1"][None, :, None, :].astype(dtype))
    y = jnp.einsum("betf,efd->betd", h, p["w2"].astype(dtype))
    y = y + p["b2"][None, :, None, :].astype(dtype)
    return jnp.einsum("bte,betd->btd", gates.astype(dtype), y)


def init(
    key,
    obs_dim=8,
    d_model=64,
    n_heads=4,
    n_layers=2,
    d_ff=None,
    n_experts=0,
    max_len=1024,
    n_kv_heads=None,
    pos_encoding="learned",
):
    """Initialize SeqFormer params.

    ``n_experts=0`` gives a dense MLP; ``n_experts>0`` the MoE variant.
    ``n_kv_heads < n_heads`` is grouped-query attention: k/v project to
    fewer heads (smaller params + KV bandwidth).  Grouped shapes are
    handled by ``full_attention`` (broadcast) and the flash kernel
    (KV-head-mapped BlockSpecs, group-summed dK/dV) behind the
    ``attn_fn`` seam; the ring sequence-parallel schemes reject them
    (their ring-level VJPs rotate per-q-head accumulators) — use
    ulysses or repeat kv heads upstream there.

    ``pos_encoding='rope'`` replaces the learned position table with
    rotary embeddings applied to q/k: positions become RELATIVE, so
    sequence length — training or :func:`rollout` horizon — is no
    longer bounded by ``max_len`` (which is then ignored), and the
    rotation happens before the ``attn_fn`` seam so every attention
    scheme (flash, windowed, GQA, ring/ulysses sequence parallelism)
    composes unchanged.  Practical horizon ~1e5-1e6 positions — f32
    angle precision, see :func:`blendjax.models.layers.rope_table`.
    """
    d_ff = d_ff or 4 * d_model
    if d_model % n_heads:
        raise ValueError(f"d_model {d_model} not divisible by n_heads {n_heads}")
    n_kv_heads = n_kv_heads or n_heads
    if n_heads % n_kv_heads:
        raise ValueError(
            f"n_heads {n_heads} not divisible by n_kv_heads {n_kv_heads}"
        )
    dh = d_model // n_heads
    if pos_encoding == "rope" and dh % 2:
        raise ValueError(f"rope needs an even head dim, got {dh}")
    if pos_encoding not in ("learned", "rope"):
        raise ValueError(f"unknown pos_encoding {pos_encoding!r}")
    keys = jax.random.split(key, 3 + n_layers)
    params = {
        "embed": dense_init(keys[0], obs_dim, d_model),
        "blocks": [],
        "ln_f": _ln_init(d_model),
        "head": dense_init(keys[2], d_model, obs_dim),
    }
    if pos_encoding == "learned":
        # absence of the table IS the rope marker: the checkpoint stays
        # a plain array pytree and remains self-describing
        params["pos"] = jax.random.normal(keys[1], (max_len, d_model)) * 0.02
    scale = jnp.sqrt(1.0 / d_model)
    for i in range(n_layers):
        ka, km = jax.random.split(keys[3 + i])
        kq, kk, kv, ko = jax.random.split(ka, 4)
        # Head-major projection layout (d, H, Dh)/(H, Dh, d): the head axis
        # is a real array axis, so tensor parallelism shards it directly
        # (seqformer_rules) and n_heads is recoverable from the shapes.
        block = {
            "ln1": _ln_init(d_model),
            "wq": {"w": jax.random.normal(kq, (d_model, n_heads, dh)) * scale,
                   "b": jnp.zeros((n_heads, dh))},
            "wk": {"w": jax.random.normal(kk, (d_model, n_kv_heads, dh))
                   * scale,
                   "b": jnp.zeros((n_kv_heads, dh))},
            "wv": {"w": jax.random.normal(kv, (d_model, n_kv_heads, dh))
                   * scale,
                   "b": jnp.zeros((n_kv_heads, dh))},
            "wo": {"w": jax.random.normal(ko, (n_heads, dh, d_model)) * scale,
                   "b": jnp.zeros((d_model,))},
            "ln2": _ln_init(d_model),
        }
        if n_experts > 0:
            block["moe"] = _moe_init(km, n_experts, d_model, d_ff)
        else:
            k1, k2 = jax.random.split(km)
            block["mlp"] = {
                "fc": dense_init(k1, d_model, d_ff),
                "proj": dense_init(k2, d_ff, d_model),
            }
        params["blocks"].append(block)
    return params


def _forward(params, obs, attn_fn, compute_dtype, moe_impl, moe_k,
             moe_capacity_factor, moe_dispatch="sort", kv_sink=None):
    """Shared forward: returns (prediction, list of per-layer MoE aux).

    ``kv_sink`` (a list) collects each layer's (k, v) projections —
    :func:`rollout`'s vectorized prefill fills its KV caches from one
    teacher-forced pass instead of t0 serial decode steps."""
    if attn_fn is None:
        def attn_fn(q, k, v):
            return full_attention(q, k, v, causal=True)

    b, t, _ = obs.shape
    auxs = []
    use_rope = "pos" not in params
    x = _dense_mq(params["embed"], obs.astype(compute_dtype), compute_dtype)
    if use_rope:
        dh = _wq_head_dim(params)
        cos, sin = rope_table(jnp.arange(t), dh)
    else:
        x = x + params["pos"][:t].astype(compute_dtype)[None]
    for blk in params["blocks"]:
        h = _ln_apply(blk["ln1"], x)
        q, k, v = (
            _proj_mq(blk[n], h, "btd,dhk->bthk", compute_dtype)
            for n in ("wq", "wk", "wv")
        )
        if use_rope:
            # rotate BEFORE the kv sink and the attn seam: caches store
            # rotated keys, and every attention scheme sees pre-rotated
            # q/k (rotation by absolute position makes scores relative)
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
        if kv_sink is not None:
            kv_sink.append((k, v))
        a = attn_fn(q, k, v)
        x = x + _proj_mq(blk["wo"], a, "bthk,hkd->btd", compute_dtype)
        h = _ln_apply(blk["ln2"], x)
        if "moe" in blk:
            if moe_impl == "topk":
                from blendjax.models.moe import moe_apply_topk

                y, aux = moe_apply_topk(
                    blk["moe"], h, compute_dtype, k=moe_k,
                    capacity_factor=moe_capacity_factor,
                    dispatch=moe_dispatch,
                )
                auxs.append(aux)
                x = x + y
            elif moe_impl == "dense":
                x = x + _moe_apply(blk["moe"], h, compute_dtype)
            else:
                raise ValueError(f"unknown moe_impl {moe_impl!r}")
        else:
            h = gelu(_dense_mq(blk["mlp"]["fc"], h, compute_dtype))
            x = x + _dense_mq(blk["mlp"]["proj"], h, compute_dtype)
    x = _ln_apply(params["ln_f"], x)
    return _dense_mq(params["head"], x, jnp.float32), auxs


def apply(params, obs, attn_fn=None, compute_dtype=jnp.bfloat16,
          moe_impl="dense", moe_k=2, moe_capacity_factor=1.25,
          moe_dispatch="sort"):
    """Forward pass: (B, T, obs_dim) -> (B, T, obs_dim) next-obs prediction.

    ``attn_fn(q, k, v) -> out`` with (B, T, H, Dh) tensors; defaults to
    single-device causal :func:`full_attention`.  Pass a
    ``make_ring_attention(mesh, causal=True, ...)`` closure to shard the
    sequence axis.  ``moe_impl``: 'dense' evaluates every expert
    (gate-weighted mixture), 'topk' routes each token to ``moe_k`` experts
    under a capacity bound (:mod:`blendjax.models.moe`).
    """
    out, _ = _forward(
        params, obs, attn_fn, compute_dtype, moe_impl, moe_k,
        moe_capacity_factor, moe_dispatch,
    )
    return out


def loss_fn(params, batch, attn_fn=None, compute_dtype=jnp.bfloat16,
            moe_impl="dense", moe_k=2, moe_capacity_factor=1.25,
            moe_aux_weight=0.0, moe_dispatch="sort"):
    """MSE next-observation loss (+ optional MoE load-balance aux term).

    ``batch = {'obs': (B,T,D), 'target': (B,T,D)}`` — the target is the
    obs sequence shifted host-side (so the device-side loss needs no
    cross-shard shift when T is sequence-sharded).  With
    ``moe_impl='topk'`` and ``moe_aux_weight > 0`` the Switch-style load
    balance loss (mean over layers) is added, pushing the router toward
    uniform expert load.
    """
    pred, auxs = _forward(
        params, batch["obs"], attn_fn, compute_dtype, moe_impl, moe_k,
        moe_capacity_factor, moe_dispatch,
    )
    err = pred - batch["target"].astype(jnp.float32)
    loss = jnp.mean(err * err)
    if auxs and moe_aux_weight:
        loss = loss + moe_aux_weight * sum(
            a["aux_loss"] for a in auxs
        ) / len(auxs)
    return loss


def moe_stats(params, batch, attn_fn=None, compute_dtype=jnp.bfloat16,
              moe_k=2, moe_capacity_factor=1.25, moe_dispatch="sort"):
    """Measured routing statistics for the topk MoE path (jit this).

    Returns ``{'dispatch_fraction': scalar, 'aux_loss': scalar}`` — means
    over layers of the fraction of (token, choice) assignments that won a
    capacity slot, and of the Switch load-balance loss.  The benchmark
    reports THIS measured fraction, not the analytic ``k/e`` bound
    (VERDICT r3 weak #3: a constant dressed as a measurement).
    """
    _, auxs = _forward(
        params, batch["obs"], attn_fn, compute_dtype, "topk", moe_k,
        moe_capacity_factor, moe_dispatch,
    )
    if not auxs:
        raise ValueError(
            "moe_stats needs params built with n_experts > 0 — these "
            "params contain no MoE blocks, so there is no routing to "
            "measure"
        )
    n = len(auxs)
    return {
        "dispatch_fraction": sum(a["dispatch_fraction"] for a in auxs) / n,
        "aux_loss": sum(a["aux_loss"] for a in auxs) / n,
    }


def make_episode_batch(obs_seq):
    """Host-side helper: episode array (B, T+1, D) -> {'obs', 'target'}."""
    return {"obs": obs_seq[:, :-1], "target": obs_seq[:, 1:]}


def episode_loss_fn(params, batch, **kwargs):
    """:func:`loss_fn` over a wire-efficient batch ``{'episode':
    (B, T+1, D)}``: the obs/target views are sliced ON DEVICE (the same
    :func:`make_episode_batch` split, applied to the traced array).

    :func:`make_episode_batch` materializes two host arrays whose
    contents overlap in all but one timestep, so a feed that transfers
    its output moves ~2x the episode's bytes host->device.  Streaming
    the raw episode and slicing device-side halves the wire traffic;
    at equal input dtype the loss is identical (parity-tested).  A feed
    may additionally downcast the episode on the wire (e.g. float16 in
    the benchmark suite) — that is a disclosed input-precision choice,
    not loss-free: the float32 target comparison then sees quantized
    targets.

    Use with replicated or batch-sharded feeds.  For SEQUENCE-sharded
    training keep the host-side :func:`make_episode_batch` split: the
    device-side shift would need a cross-shard neighbor exchange there
    (see :func:`loss_fn`'s note on the sharded target).
    """
    return loss_fn(params, make_episode_batch(batch["episode"]), **kwargs)


def train_flops(batch_size, seq_len, obs_dim, d_model, n_heads, n_layers,
                d_ff=None, n_experts=0, moe_impl="dense", moe_k=2,
                moe_capacity_factor=1.25):
    """Closed-form FLOPs of one training step (matmul terms only).

    Forward, per token: qkv+out projections ``8*d^2``, attention scores +
    apply ``4*T*d`` (full T^2 — :func:`full_attention` computes the whole
    matrix and masks, so the causal half is NOT discounted; a kernel that
    skips masked blocks, e.g. the Pallas flash path, will show mfu ~2x
    against this count and the benchmark reports both counts so that is
    visible), MLP ``4*d*d_ff``.  MoE: 'dense' evaluates every expert
    (``n_experts * 4*d*d_ff`` + gate); 'topk' fills ``e*capacity =
    ~k*cf*n`` arena rows, so expert compute is ``k*cf`` times the single
    -MLP term regardless of routing collapse (static shapes).  Training
    = 3x forward; embed/head/layernorm/optimizer terms included where
    matmul-shaped, elementwise omitted.  Cross-checked against XLA's
    ``cost_analysis()`` by the benchmark suite (VERDICT r3 next #2).
    """
    B, T, d = batch_size, seq_len, d_model
    d_ff = d_ff or 4 * d
    tok = B * T
    fwd = 2.0 * tok * obs_dim * d  # embed
    per_layer = 8.0 * d * d + 4.0 * T * d  # qkvo + scores/apply per token
    if n_experts > 0:
        gate = 2.0 * d * n_experts
        if moe_impl == "topk":
            # static arena: e * ceil(k*n/e * cf) rows through the expert MLP
            import math

            cap = max(1, math.ceil(moe_k * tok / n_experts
                                   * moe_capacity_factor))
            expert_rows = n_experts * cap
            mlp = gate + 4.0 * d * d_ff * (expert_rows / tok)
        else:
            mlp = gate + n_experts * 4.0 * d * d_ff
    else:
        mlp = 4.0 * d * d_ff
    fwd += tok * n_layers * (per_layer + mlp)
    fwd += 2.0 * tok * d * obs_dim  # head
    return 3.0 * fwd


# -- autoregressive rollout (KV cache) --------------------------------------


def init_cache(params, batch_size, dtype=jnp.bfloat16, length=None,
               per_row=False):
    """Per-layer KV caches: ``{'k': [(B, L, Hkv, Dh)], 'v': [...],
    'pos': 0}``.  ``length`` defaults to the model's ``max_len`` (the
    ``pos`` table); pass the actual decode horizon to size the cache —
    and every step's attention — to the sequence you will run.  Rope
    models have no table and no inherent bound: ``length`` is required.

    ``per_row=True`` makes ``pos`` a ``(B,)`` int32 vector instead of
    the batch-uniform scalar: every cache row then decodes at its OWN
    position (:func:`decode_step` dispatches on ``pos``'s rank), which
    is what a serving tier needs to run ONE batched decode over live
    episodes at heterogeneous timesteps (``blendjax/serve``).  Resetting
    a single episode is ``cache['pos'].at[i].set(0)`` — stale k/v rows
    need no zeroing because :func:`_attn_one` masks by each slot's
    absolute position, which turns negative the moment the row's
    position rewinds.
    """
    if length is None:
        if "pos" not in params:
            raise ValueError(
                "rope models have no max_len; pass the decode horizon "
                "as length="
            )
        length = params["pos"].shape[0]
    elif "pos" in params and length > params["pos"].shape[0]:
        # decode_step indexes the pos table with a traced position;
        # lax.dynamic_index_in_dim CLAMPS out-of-bounds, so steps past
        # max_len would silently reuse the last embedding — reject the
        # intent here, statically
        raise ValueError(
            f"cache length {length} exceeds the learned position "
            f"table ({params['pos'].shape[0]}); use pos_encoding='rope' "
            "for longer horizons"
        )
    pos0 = (
        jnp.zeros((batch_size,), jnp.int32)
        if per_row else jnp.asarray(0, jnp.int32)
    )
    caches = {"k": [], "v": [], "pos": pos0}
    for blk in params["blocks"]:
        wk = blk["wk"]
        _, h_kv, dh = (wk["w"] if "w" in wk else wk["w_q"]).shape
        shape = (batch_size, length, h_kv, dh)
        caches["k"].append(jnp.zeros(shape, dtype))
        caches["v"].append(jnp.zeros(shape, dtype))
    return caches


def _attn_one(q, kc, vc, pos, scale, window=None):
    """Single-query attention over a (possibly ring-buffer) cache: q
    (B, H, Dh), kc/vc (B, C, Hkv, Dh).  The cache is written at
    ``slot = p % C``, so slot ``s`` currently holds absolute position
    ``pos - ((pos - s) mod C)`` — the latest position congruent to
    ``s`` that has been written.  Masking on that absolute position
    unifies the no-wrap case (C >= sequence: it reduces to ``s <= pos``)
    with the O(window)-memory ring (C >= window: overwritten slots fall
    outside the window by construction).  GQA broadcasts the cached
    heads.

    ``pos`` is either the batch-uniform scalar (training rollouts) or a
    ``(B,)`` vector — one position per row, giving a (B, C) mask so one
    batched decode serves episodes at heterogeneous timesteps (the
    serving tier's path).  The scalar branch is the exact pre-serving
    code: rollout numerics are untouched."""
    b, c, h_kv, dh = kc.shape
    h = q.shape[1]
    pos = jnp.asarray(pos)
    if pos.ndim == 0:
        slot_pos = pos - ((pos - jnp.arange(c)) % c)
        keep = slot_pos >= 0  # never-written slots: negative positions
        if window is not None:
            keep = jnp.logical_and(keep, slot_pos > pos - window)
        keep_g = keep[None, None, None]   # over (B, Hkv, G, C)
        keep_h = keep[None, None]         # over (B, H, C)
    else:
        p_col = pos[:, None]              # (B, 1)
        slot_pos = p_col - ((p_col - jnp.arange(c)[None]) % c)
        keep = slot_pos >= 0              # (B, C)
        if window is not None:
            keep = jnp.logical_and(keep, slot_pos > p_col - window)
        keep_g = keep[:, None, None, :]
        keep_h = keep[:, None, :]
    if h_kv != h:
        # grouped einsum straight against the un-repeated cache —
        # materializing a repeated copy per decode step would pay
        # exactly the KV bandwidth GQA exists to avoid
        g = h // h_kv
        qg = q.reshape(b, h_kv, g, dh).astype(jnp.float32)
        s = jnp.einsum("bkgd,blkd->bkgl", qg,
                       kc.astype(jnp.float32)) * scale
        s = jnp.where(keep_g, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bkgl,blkd->bkgd", p, vc.astype(jnp.float32))
        return out.reshape(b, h, dh)
    s = jnp.einsum("bhd,blhd->bhl", q.astype(jnp.float32),
                   kc.astype(jnp.float32)) * scale
    s = jnp.where(keep_h, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhl,blhd->bhd", p, vc.astype(jnp.float32))


def decode_step(params, cache, obs_t, compute_dtype=jnp.bfloat16,
                moe_impl="dense", moe_k=2, moe_capacity_factor=1.25,
                moe_dispatch="sort", window=None):
    """One incremental step: consume obs_t (B, obs_dim) at the cache's
    current position, return (next-obs prediction (B, obs_dim) float32,
    updated cache).  Mirrors :func:`_forward`'s block math exactly at a
    single position — parity with the teacher-forced forward is tested.

    The cache is a RING buffer: writes land at ``pos % C`` and masking
    is by each slot's absolute position (see :func:`_attn_one`), so a
    cache of ``C >= window`` slots supports an unbounded decode horizon
    at O(window) memory.  A cache shorter than the sequence with NO
    window effectively attends to the last ``C`` positions only —
    size the cache to the horizon (what :func:`rollout` does) unless
    you want exactly that.

    ``cache['pos']`` may be a ``(B,)`` vector (``init_cache(...,
    per_row=True)``): each row then embeds, rotates, writes its ring
    slot and masks at its OWN position, so one batched call decodes
    episodes at heterogeneous timesteps — the policy-serving tier's
    continuous-batching kernel (parity with per-episode scalar decode
    is locked by ``tests/test_serve.py``).  The scalar path is
    byte-for-byte the pre-serving code.
    """
    from jax import lax

    pos = cache["pos"]
    per_row = jnp.ndim(pos) == 1
    use_rope = "pos" not in params
    x = _dense_mq(params["embed"], obs_t.astype(compute_dtype),
                  compute_dtype)
    if use_rope:
        cos, sin = rope_table(pos if per_row else pos[None],
                              _wq_head_dim(params))
    elif per_row:
        # per-row table lookup; clip mirrors dynamic_index_in_dim's
        # out-of-bounds clamp on the scalar path (init_cache rejects
        # horizons past the table statically)
        x = x + jnp.take(params["pos"], pos, axis=0,
                         mode="clip").astype(compute_dtype)
    else:
        x = x + lax.dynamic_index_in_dim(
            params["pos"], pos, keepdims=False
        ).astype(compute_dtype)[None]
    new_cache = {"k": [], "v": [], "pos": pos + 1}
    rows = jnp.arange(obs_t.shape[0]) if per_row else None
    for i, blk in enumerate(params["blocks"]):
        h = _ln_apply(blk["ln1"], x)
        q = _proj_mq(blk["wq"], h, "bd,dhk->bhk", compute_dtype)
        k_new = _proj_mq(blk["wk"], h, "bd,dhk->bhk", compute_dtype)
        v_new = _proj_mq(blk["wv"], h, "bd,dhk->bhk", compute_dtype)
        if use_rope:
            if per_row:
                q = apply_rope_rows(q, cos, sin)
                k_new = apply_rope_rows(k_new, cos, sin)
            else:
                q = apply_rope(q, cos, sin)
                k_new = apply_rope(k_new, cos, sin)
        slot = pos % cache["k"][i].shape[1]  # ring buffer (see _attn_one)
        if per_row:
            # scatter each row's k/v at ITS ring slot
            kc = cache["k"][i].at[rows, slot].set(
                k_new.astype(cache["k"][i].dtype)
            )
            vc = cache["v"][i].at[rows, slot].set(
                v_new.astype(cache["v"][i].dtype)
            )
        else:
            kc = lax.dynamic_update_slice_in_dim(
                cache["k"][i], k_new[:, None].astype(cache["k"][i].dtype),
                slot, axis=1,
            )
            vc = lax.dynamic_update_slice_in_dim(
                cache["v"][i], v_new[:, None].astype(cache["v"][i].dtype),
                slot, axis=1,
            )
        new_cache["k"].append(kc)
        new_cache["v"].append(vc)
        dh = q.shape[-1]
        a = _attn_one(q, kc, vc, pos, 1.0 / jnp.sqrt(dh),
                      window=window).astype(compute_dtype)
        x = x + _proj_mq(blk["wo"], a, "bhk,hkd->bd", compute_dtype)
        h = _ln_apply(blk["ln2"], x)
        if "moe" in blk:
            h3 = h[:, None]  # the moe layers take (B, T, d)
            if moe_impl == "topk":
                from blendjax.models.moe import moe_apply_topk

                # decode-time routing is DROP-FREE: the capacity bound
                # exists to balance batched training dispatch, and its
                # value depends on the total token count — so
                # capacity-bounded routing is not causal and can never
                # match between incremental and full-sequence evaluation.
                # cf >= e/k guarantees a slot for every assignment here.
                e = blk["moe"]["w1"].shape[0]
                y, _ = moe_apply_topk(
                    blk["moe"], h3, compute_dtype, k=moe_k,
                    capacity_factor=max(moe_capacity_factor,
                                        e / min(moe_k, e)),
                    dispatch=moe_dispatch,
                )
            elif moe_impl == "dense":
                y = _moe_apply(blk["moe"], h3, compute_dtype)
            else:
                raise ValueError(f"unknown moe_impl {moe_impl!r}")
            x = x + y[:, 0]
        else:
            h = gelu(_dense_mq(blk["mlp"]["fc"], h, compute_dtype))
            x = x + _dense_mq(blk["mlp"]["proj"], h, compute_dtype)
    x = _ln_apply(params["ln_f"], x)
    return _dense_mq(params["head"], x, jnp.float32), new_cache


def rollout(params, prefix, n_steps, compute_dtype=jnp.bfloat16,
            moe_impl="dense", moe_k=2, moe_capacity_factor=1.25,
            moe_dispatch="sort", window=None, cache_dtype=None):
    """Autoregressive world-model rollout ("dreaming"): consume the
    ``prefix`` episode (B, T0, obs_dim), then feed the model its own
    next-observation predictions for ``n_steps`` more steps.

    Returns (B, n_steps, obs_dim) float32 predictions for positions
    T0 .. T0+n_steps-1.  Incremental per-step cost is O(cache) attention
    over the KV cache instead of re-running the O(T^2) forward on the
    growing sequence; under a ``window`` the cache is a RING BUFFER of
    ``window`` slots, so memory stays O(window) however long the dream
    (with ``pos_encoding='rope'`` the horizon is then bounded only by
    rope's f32 angle precision).  Parity with the naive re-run is
    tested.  Jit-compatible (the phases are one teacher-forced pass and
    a ``lax.scan``).

    The reference has no sequence models, let alone an inference path
    (SURVEY.md §5); this completes the world-model workload the
    framework adds.
    """
    b, t0, obs_dim = prefix.shape
    if n_steps < 1:
        raise ValueError(f"n_steps must be >= 1, got {n_steps}")
    if t0 < 1:
        raise ValueError("prefix must contain at least one observation")
    if "pos" in params and t0 + n_steps > params["pos"].shape[0]:
        # rope models ("pos" absent) have no table and no length bound
        raise ValueError(
            f"prefix {t0} + rollout {n_steps} exceeds max_len "
            f"{params['pos'].shape[0]}"
        )
    from jax import lax

    # drop-free MoE routing on BOTH phases (see decode_step): routing
    # must be per-token independent for the vectorized prefill and the
    # incremental decode to agree
    cf = moe_capacity_factor
    for blk in params["blocks"]:
        if "moe" in blk:
            e = blk["moe"]["w1"].shape[0]
            cf = max(cf, e / min(moe_k, e))
            break
    step_kwargs = dict(
        compute_dtype=compute_dtype, moe_impl=moe_impl, moe_k=moe_k,
        moe_capacity_factor=cf, moe_dispatch=moe_dispatch, window=window,
    )

    # vectorized prefill: ONE teacher-forced pass fills every layer's
    # KV cache (the standard prefill/decode split) — not t0 serial
    # decode steps
    kvs = []
    preds, _ = _forward(
        params, prefix,
        lambda q, k, v: full_attention(q, k, v, causal=True,
                                       window=window),
        compute_dtype, moe_impl, moe_k, cf, moe_dispatch, kv_sink=kvs,
    )
    last_pred = preds[:, -1]  # prediction for position t0
    cache_dt = cache_dtype or compute_dtype
    total = t0 + n_steps
    # windowed: a ring buffer of `window` slots bounds memory at
    # O(window) no matter the horizon (decode_step writes at pos % C,
    # _attn_one masks by slot position)
    length = total if window is None else min(total, window)
    cache = init_cache(params, b, dtype=cache_dt, length=length)
    cache["pos"] = jnp.asarray(t0, jnp.int32)
    # keep only the prefix tail that fits the ring, placed at each
    # position's slot (distinct since we keep <= C consecutive ones)
    keep_n = min(t0, length)
    slots = (jnp.arange(keep_n) + (t0 - keep_n)) % length
    for i, (k, v) in enumerate(kvs):
        cache["k"][i] = cache["k"][i].at[:, slots].set(
            k[:, t0 - keep_n:].astype(cache_dt)
        )
        cache["v"][i] = cache["v"][i].at[:, slots].set(
            v[:, t0 - keep_n:].astype(cache_dt)
        )

    def dream(carry, _):
        cache, obs_t = carry
        pred, cache = decode_step(params, cache, obs_t, **step_kwargs)
        return (cache, pred), obs_t

    (_, final), dreamed = lax.scan(
        dream, (cache, last_pred), None, length=n_steps - 1
    )
    out = jnp.concatenate([dreamed, final[None]], axis=0)
    return out.transpose(1, 0, 2)
