"""Supervised learner respawn: the training run's own watchdog.

:class:`LearnerProcess` is the launcher-compatible surface (duck-typed
``launch_info`` + ``respawn(idx)``) wrapping one ``python -m
blendjax.ha.learner`` child, so :class:`~blendjax.btt.watchdog.
FleetWatchdog` supervises the learner exactly like Blender producers,
replay shards and serve replicas.  :class:`LearnerSupervisor` ties the
watchdog to the HA vocabulary: a death counts ``ha_learner_deaths`` and
dumps a flight-recorder postmortem naming the dead learner with its
last ``stats()`` digest attached (the mirror the
:class:`~blendjax.ha.checkpoint.TrainCheckpointer` keeps on disk — a
SIGKILLed process cannot be asked anything); a successful respawn
counts ``ha_learner_respawns``.  The RESUME itself is the child's
startup behavior (restore the latest complete manifest, republish the
checkpointed weights under a fresh higher version id) — the supervisor
only has to bring the process back.

See docs/fault_tolerance.md "Learner failover".
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import time

from blendjax.btt.watchdog import FleetWatchdog
from blendjax.obs.flight import default_postmortem_dir, flight_recorder
from blendjax.utils.timing import HA_EVENTS, fleet_counters

logger = logging.getLogger("blendjax")


class _LearnerLaunchInfo:
    """Duck-typed ``launch_info`` so :class:`~blendjax.btt.watchdog.
    FleetWatchdog` supervises the learner process exactly like every
    other tier's children."""

    def __init__(self, processes):
        self.processes = processes
        self.addresses = {}


class LearnerProcess:
    """One supervised learner *process* (``python -m blendjax.ha.
    learner``) with a launcher-compatible surface, so
    ``FleetWatchdog(restart=True)`` respawns it after a SIGKILL with
    its original command line.  The respawned child resumes from the
    latest complete manifest under ``ckpt_dir`` on its own.

    Params mirror the child's CLI (see :mod:`blendjax.ha.learner`);
    ``extra_args`` passes anything not spelled out here."""

    def __init__(self, *, ckpt_dir, env_addresses=(), replay_shards=(),
                 shard_capacity=None, weight_bus=None, publish_every=1,
                 obs_dim=1, num_actions=2, rollout_len=8, seed=0,
                 replay_ratio=0, replay_batch=32, ckpt_every=2,
                 ckpt_seconds=None, updates=0, chunk_updates=4,
                 action_values=None, probe_batch=0, timeoutms=15000,
                 python=None, ready_timeout=90.0, extra_args=()):
        self.ckpt_dir = os.path.abspath(ckpt_dir)
        os.makedirs(self.ckpt_dir, exist_ok=True)
        self.stats_path = os.path.join(self.ckpt_dir,
                                       "learner_stats.json")
        self.python = python or sys.executable
        self.ready_timeout = ready_timeout
        self._cmd = [
            self.python, "-m", "blendjax.ha.learner",
            "--ckpt-dir", self.ckpt_dir,
            "--obs-dim", str(obs_dim),
            "--num-actions", str(num_actions),
            "--rollout-len", str(rollout_len),
            "--seed", str(seed),
            "--ckpt-every", str(ckpt_every),
            "--chunk-updates", str(chunk_updates),
            "--timeoutms", str(timeoutms),
        ]
        if env_addresses:
            self._cmd += ["--envs", ",".join(env_addresses)]
        if replay_shards:
            self._cmd += ["--replay-shards", ",".join(replay_shards)]
            self._cmd += ["--replay-ratio", str(replay_ratio),
                          "--replay-batch", str(replay_batch)]
        if shard_capacity is not None:
            self._cmd += ["--shard-capacity", str(shard_capacity)]
        if weight_bus:
            self._cmd += ["--weight-bus", weight_bus,
                          "--publish-every", str(publish_every)]
        if ckpt_seconds is not None:
            self._cmd += ["--ckpt-seconds", str(ckpt_seconds)]
        if updates:
            self._cmd += ["--updates", str(updates)]
        if action_values is not None:
            self._cmd += [
                "--action-values",
                ",".join(str(float(v)) for v in action_values),
            ]
        if probe_batch:
            self._cmd += ["--probe-batch", str(probe_batch)]
        self._cmd += list(extra_args)
        self.launch_info = None

    def _spawn(self):
        from blendjax.btt.launcher import child_env

        env = child_env()
        # the learner is a jax process pinned to CPU in tests/benches;
        # a dead TPU tunnel relay must not hang its (re)start — the
        # same rationale as the serve/shard children
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        return subprocess.Popen(self._cmd, env=env,
                                start_new_session=True)

    def __enter__(self):
        self.launch_info = _LearnerLaunchInfo([self._spawn()])
        try:
            self.wait_ready(self.ready_timeout)
        except BaseException:
            self.close()
            raise
        return self

    def read_stats(self):
        """The child's latest stats mirror (None when absent or torn —
        the atomic-rename write makes torn reads rare, not impossible
        against a different filesystem)."""
        try:
            with open(self.stats_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def wait_ready(self, timeout=90.0):
        """Block until the CURRENT child wrote a stats mirror (its
        ready barrier — after the jax import, the restore, and the
        resume republish)."""
        proc = self.launch_info.processes[0]
        deadline = time.monotonic() + timeout
        while True:
            stats = self.read_stats()
            if stats is not None and stats.get("pid") == proc.pid:
                return stats
            if proc.poll() is not None:
                raise RuntimeError(
                    f"learner process exited with {proc.returncode} "
                    "before becoming ready"
                )
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"learner process not ready within {timeout:.1f}s"
                )
            time.sleep(0.05)

    def respawn(self, idx=0):
        """Relaunch with the original command line (the watchdog's
        contract); the child restores the latest complete manifest on
        its own."""
        proc = self._spawn()
        self.launch_info.processes[idx] = proc
        return proc

    def close(self):
        info = self.launch_info
        if info is None:
            return
        for p in info.processes:
            try:
                p.terminate()
            except Exception:  # noqa: BLE001
                pass
        for p in info.processes:
            try:
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001
                try:
                    p.kill()
                except Exception:  # noqa: BLE001
                    pass
        self.launch_info = None

    def __exit__(self, *exc):
        self.close()
        return False


class LearnerSupervisor:
    """Death detection + respawn + postmortem for the learner process.

    Params
    ------
    process: LearnerProcess
        Inside its context (``launch_info`` populated).
    interval: float
        Watchdog poll period, seconds.
    restart: bool
        Respawn the dead learner (off = detect/postmortem only).
    counters: EventCounters | None
        ``HA_EVENTS`` sink; process-wide default when omitted.
    postmortem_dir: str | None
        Postmortem destination (defaults to ``$BJX_POSTMORTEM_DIR``).
    on_death / on_respawn: callable | None
        Extra user hooks, invoked after the supervisor's own handling.
    """

    def __init__(self, process, *, interval=0.5, restart=True,
                 counters=None, postmortem_dir=None, on_death=None,
                 on_respawn=None):
        self.process = process
        self.counters = counters if counters is not None else fleet_counters
        self.postmortem_dir = (
            postmortem_dir if postmortem_dir is not None
            else default_postmortem_dir()
        )
        self.last_postmortem = None
        self._user_on_death = on_death
        self._user_on_respawn = on_respawn
        self.watchdog = FleetWatchdog(
            process, interval=interval, on_death=self._on_death,
            restart=restart, on_respawn=self._on_respawn,
        )

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        self.watchdog.start()
        return self

    def stop(self):
        self.watchdog.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- death -> postmortem -> respawn --------------------------------------

    def _on_death(self, idx, code):
        self.counters.incr("ha_learner_deaths")
        stats = self.process.read_stats() or {}
        flight_recorder.note(
            "learner_death", target="learner", exit_code=code,
            updates=stats.get("updates"),
            last_ckpt_update=stats.get("last_ckpt_update"),
        )
        logger.warning(
            "learner process died (exit %s) at update %s (last "
            "checkpoint cut: update %s); %s", code,
            stats.get("updates"), stats.get("last_ckpt_update"),
            "respawning" if self.watchdog.restart
            else "restart disabled",
        )
        if self.postmortem_dir is not None:
            # the dead learner cannot be asked anything — attach the
            # stats mirror the checkpointer kept on disk, so the
            # postmortem names the learner AND its last known state
            self.last_postmortem = flight_recorder.dump(
                directory=self.postmortem_dir,
                reason="death-learner",
                extra={
                    "target": "learner",
                    "exit_code": code,
                    "stats": stats,
                    "ckpt_dir": self.process.ckpt_dir,
                },
            )
        if self._user_on_death is not None:
            self._user_on_death(idx, code)

    def _on_respawn(self, idx, proc):
        self.counters.incr("ha_learner_respawns")
        flight_recorder.note(
            "learner_respawn", target="learner", pid=proc.pid,
        )
        if self._user_on_respawn is not None:
            self._user_on_respawn(idx, proc)

    # -- observability -------------------------------------------------------

    def _await(self, cond, timeout):
        deadline = time.monotonic() + timeout
        while not cond():
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.05)
        return True

    def await_deaths(self, n=1, timeout=30.0):
        return self._await(
            lambda: self.counters.get("ha_learner_deaths") >= n, timeout
        )

    def await_respawns(self, n=1, timeout=30.0):
        return self._await(
            lambda: self.counters.get("ha_learner_respawns") >= n,
            timeout,
        )

    def health(self):
        """Zero-filled ``HA_EVENTS`` + watchdog liveness + the child's
        latest stats mirror — the one-snapshot contract every other
        supervisor keeps, pointed at the learner."""
        h = dict.fromkeys(HA_EVENTS, 0)
        h.update(self.counters.snapshot())
        h["alive"] = self.watchdog.alive
        h["learner_stats"] = self.process.read_stats()
        return h
