"""The supervised learner process: ``python -m blendjax.ha.learner``.

The launcher surface :class:`~blendjax.ha.supervisor.LearnerProcess`
spawns (and ``FleetWatchdog(restart=True)`` respawns).  Startup IS the
resume path:

1. find the latest complete manifest under ``--ckpt-dir``
   (:func:`blendjax.ha.checkpoint.latest_manifest` — damaged cuts are
   skipped, counted, warned);
2. rebuild the replay draw authority from the cut
   (:func:`~blendjax.ha.checkpoint.restore_replay`: the shards
   survived, so the restore reconciles the slots the dead incarnation
   appended past the cut out of the draw domain — the resumed actors
   rewrite them);
3. bind the weight bus at the SAME address with the default wall-clock
   ``version_base`` and republish the checkpointed params under a
   fresh HIGHER version id — subscribed serve replicas heal through
   their periodic re-sync and roll forward, clients observe a
   monotonic version stream with zero errors;
4. reconnect the producer fleet (the producers never died — a fresh
   :class:`~blendjax.btt.envpool.EnvPool` dials the same addresses)
   and train on, with the scenario assignment re-pushed and the update
   counter, curriculum and RNG-bearing replay state continuing from
   the cut.

A fresh directory (no manifest) starts training from scratch through
the exact same code path.  The checkpointer mirrors ``stats()`` to
``<ckpt-dir>/learner_stats.json`` every update — the supervisor's
postmortem source and the recovery benchmark's clock.

See docs/fault_tolerance.md "Learner failover".
"""

from __future__ import annotations

import argparse
import hashlib
import logging
import os
import signal
import threading

import numpy as np

logger = logging.getLogger("blendjax")


def build_parser():
    ap = argparse.ArgumentParser(
        description="Supervised blendjax learner (resumes from the "
                    "latest complete HA manifest at startup)."
    )
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--envs", default="",
                    help="comma-separated producer GYM addresses (empty "
                         "= fleet-less: train off-policy from the "
                         "replay shards alone)")
    ap.add_argument("--replay-shards", default="",
                    help="comma-separated replay shard addresses")
    ap.add_argument("--shard-capacity", type=int, default=None)
    ap.add_argument("--weight-bus", default=None,
                    help="weight-bus BIND address (fixed port, so a "
                         "respawned learner re-binds where the "
                         "subscribers already dial)")
    ap.add_argument("--publish-every", type=int, default=1)
    ap.add_argument("--obs-dim", type=int, default=1)
    ap.add_argument("--num-actions", type=int, default=2)
    ap.add_argument("--rollout-len", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--replay-ratio", type=int, default=0)
    ap.add_argument("--replay-batch", type=int, default=32)
    ap.add_argument("--ckpt-every", type=int, default=2,
                    help="checkpoint cadence in completed updates")
    ap.add_argument("--ckpt-seconds", type=float, default=None)
    ap.add_argument("--updates", type=int, default=0,
                    help="stop once the (resumed) update counter "
                         "reaches this (0 = run until signalled)")
    ap.add_argument("--chunk-updates", type=int, default=4,
                    help="updates per run() chunk between stop checks")
    ap.add_argument("--offline-batch", type=int, default=32)
    ap.add_argument("--timeoutms", type=int, default=15000)
    ap.add_argument("--action-values", default=None,
                    help="comma-separated floats mapping the discrete "
                         "action index to the producers' action space")
    ap.add_argument("--probe-batch", type=int, default=0,
                    help="after a resume, draw one probe batch of this "
                         "size from the restored replay and record its "
                         "index digest in the stats mirror (evidence "
                         "that every acked row is still drawable)")
    return ap


def main(argv=None):
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    from blendjax.ha.checkpoint import (
        TrainCheckpointer,
        latest_manifest,
        restore_replay,
    )
    from blendjax.utils.timing import fleet_counters

    counters = fleet_counters
    manifest = latest_manifest(args.ckpt_dir, counters=counters)

    shard_addrs = [a for a in args.replay_shards.split(",") if a]
    env_addrs = [a for a in args.envs.split(",") if a]

    replay = None
    if shard_addrs:
        from blendjax.replay.shard_client import ShardedReplay

        if manifest is not None and manifest.get("replay"):
            replay = restore_replay(
                manifest, shard_addrs, counters=counters,
                timeoutms=args.timeoutms,
            )
        else:
            replay = ShardedReplay(
                shard_addrs, seed=args.seed, counters=counters,
                timeoutms=args.timeoutms,
                shard_capacity=args.shard_capacity,
            )

    bus = None
    if args.weight_bus:
        from blendjax.weights.bus import WeightPublisher

        # default (wall-clock) version_base ON PURPOSE: a respawned
        # publisher must start above its predecessor so subscribers —
        # who never adopt backwards — roll forward (docs/weight_bus.md)
        bus = WeightPublisher(args.weight_bus,
                              counters=counters).start()

    pool = None
    if env_addrs:
        from blendjax.btt.envpool import EnvPool

        pool = EnvPool(env_addrs, timeoutms=args.timeoutms,
                       autoreset=True, counters=counters)

    ckptr = TrainCheckpointer(
        args.ckpt_dir, every_updates=args.ckpt_every,
        every_seconds=args.ckpt_seconds, counters=counters,
    )

    action_map = None
    if args.action_values:
        values = np.array(
            [float(v) for v in args.action_values.split(",")],
            np.float64,
        )
        action_map = lambda a: list(values[np.asarray(a)])  # noqa: E731

    from blendjax.models.actor_learner import ActorLearner

    learner = ActorLearner(
        pool, args.obs_dim, args.num_actions,
        rollout_len=args.rollout_len, seed=args.seed,
        action_map=action_map, replay=replay,
        replay_ratio=(args.replay_ratio if replay is not None else 0),
        replay_batch=args.replay_batch,
        weight_bus=bus, publish_every=args.publish_every,
        checkpointer=ckptr,
    )

    ckptr.stats_extra["pid"] = os.getpid()
    resumed_from = None
    if manifest is not None:
        ckptr.restore(learner, manifest)  # republish included
        resumed_from = int(manifest["update"])
        ckptr.stats_extra["resumed_from"] = resumed_from
        if args.probe_batch and replay is not None:
            # the first post-resume draw, before any actor appends: a
            # successful stratified draw over the restored domain is
            # the "every acked row still drawable" witness, and its
            # digest is deterministic given the cut
            try:
                _, idx, _ = replay.sample(
                    args.probe_batch, timeout=0.0
                )
                ckptr.stats_extra["probe_digest"] = hashlib.sha1(
                    np.ascontiguousarray(idx, np.int64).tobytes()
                ).hexdigest()[:16]
            except TimeoutError:
                ckptr.stats_extra["probe_digest"] = "underfilled"
    elif bus is not None:
        # fresh start: put version 1 on the bus before the first
        # update so late-joining subscribers have a full sync target
        import jax

        learner.last_published_version = bus.publish(
            jax.device_get(learner.state.params), step=0
        )

    stop = threading.Event()

    def _term(signum, frame):
        stop.set()
        learner._stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)

    # the ready barrier LearnerProcess.wait_ready polls for
    ckptr._write_stats(learner, force=True)
    logger.info(
        "HA learner ready (pid %d): resumed_from=%s updates=%d "
        "envs=%d shards=%d bus=%s", os.getpid(), resumed_from,
        learner._updates_done, len(env_addrs), len(shard_addrs),
        getattr(bus, "address", None),
    )

    try:
        while not stop.is_set():
            if args.updates and learner._updates_done >= args.updates:
                break
            chunk = args.chunk_updates
            if args.updates:
                chunk = min(
                    chunk, args.updates - learner._updates_done
                )
            if pool is not None:
                # seconds= bounds the chunk so a SIGTERM mid-chunk (the
                # single-fleet loop only checks update/deadline limits)
                # ends within one window instead of hanging
                learner.run(num_updates=chunk, seconds=10.0)
            else:
                learner.run_offline(num_updates=chunk,
                                    batch_size=args.offline_batch)
            ckptr._write_stats(learner, force=True)
    finally:
        ckptr.join(timeout=30)
        ckptr._write_stats(learner, force=True)
        if pool is not None:
            pool.close()
        if bus is not None:
            bus.close()
        if replay is not None and hasattr(replay, "close"):
            replay.close()


if __name__ == "__main__":
    main()
