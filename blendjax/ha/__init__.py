"""Learner failover (HA): the last single point of failure closed.

Every other tier already survives a SIGKILL — fleets respawn and rejoin
(:mod:`blendjax.btt.supervise`), replay shards restore crash-exact from
spill (:mod:`blendjax.replay.service`), serve replicas respawn under the
watchdog, a killed weight publisher is invisible to its clients — but
the one process that OWNS the training run had no checkpoint, no resume
and no supervisor.  This package adds all three:

- :class:`~blendjax.ha.checkpoint.TrainCheckpointer` — a coordinated,
  atomic, versioned snapshot of the whole learner-side state (TrainState
  + update counter + curriculum + the replay client's draw authority +
  the last published weight-bus version), taken asynchronously off the
  update loop and committed by a manifest naming one consistent cut;
- ``python -m blendjax.ha.learner`` — the supervised learner process
  (:mod:`blendjax.ha.learner`): restores the latest complete manifest at
  startup, republishes the checkpointed weights under a fresh higher
  version id, and trains on;
- :class:`~blendjax.ha.supervisor.LearnerSupervisor` /
  :class:`~blendjax.ha.supervisor.LearnerProcess` — the launcher-
  compatible surface ``FleetWatchdog(restart=True)`` respawns, with a
  flight-recorder postmortem naming the dead learner.

See docs/fault_tolerance.md "Learner failover".
"""

from blendjax.ha.checkpoint import (  # noqa: F401
    MANIFEST_FORMAT,
    TrainCheckpointer,
    latest_manifest,
    restore_replay,
)
from blendjax.ha.supervisor import (  # noqa: F401
    LearnerProcess,
    LearnerSupervisor,
)

__all__ = [
    "MANIFEST_FORMAT",
    "TrainCheckpointer",
    "latest_manifest",
    "restore_replay",
    "LearnerProcess",
    "LearnerSupervisor",
]
