"""TrainCheckpointer: one consistent cut of the whole learner-side state.

The learner owns five kinds of state that must agree for a resume the
rest of the system cannot distinguish from no crash:

1. the **TrainState** (params + optimizer state) — serialized through
   the existing :class:`blendjax.utils.checkpoint.CheckpointManager`
   (fsync + atomic rename since ISSUE-15, so a host crash never leaves
   a complete-looking truncated file);
2. the **update counter / seed / last published weight-bus version** —
   small scalars riding inline in the manifest
   (:meth:`blendjax.models.actor_learner.ActorLearner.checkpoint_state`);
3. the **curriculum** (:meth:`blendjax.scenario.CurriculumScheduler.
   state_dict`) and the per-fleet **scenario assignments**;
4. the **replay draw authority** — :meth:`ShardedReplay.save` already
   snapshots the client AND every live shard under one lock; it is
   called inside the same barrier as the TrainState host-gather, so the
   checkpoint's replay cursor and the learner step form one cut;
5. the **manifest** — a JSON file written (fsynced) LAST, naming the
   component files of the cut.  A checkpoint exists iff its manifest
   does; a crash mid-checkpoint leaves the previous manifest intact.

Checkpoints are taken **asynchronously off the update loop**: the
synchronous barrier (measured as ``ha_snapshot``) host-gathers the
TrainState the same way ``_publish_params`` does and takes the replay
cut; the npz serialization, manifest commit and retention run in a
background thread (``ha_serialize``).  A checkpoint that comes due
while the previous serialization is still in flight is SKIPPED and
counted (``ha_ckpt_skipped``) — the update loop never queues up
checkpoint work, which is the bounded-stall contract the
``ckpt_overhead_x`` benchmark prices at ~1.0.

See docs/fault_tolerance.md "Learner failover".
"""

from __future__ import annotations

import glob
import json
import logging
import os
import threading
import time

import numpy as np

from blendjax.obs.flight import flight_recorder
from blendjax.utils.checkpoint import CheckpointManager, _replace_durable
from blendjax.utils.timing import StageTimer, fleet_counters

logger = logging.getLogger("blendjax")

#: Manifest format tag — the commit record of one consistent cut.
MANIFEST_FORMAT = "blendjax.ha.manifest/1"


def _write_json_durable(path, doc):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    _replace_durable(tmp, path)


def _manifest_paths(directory):
    return sorted(glob.glob(os.path.join(directory, "manifest_*.json")))


def _verify_npz(path):
    """Integrity probe of a component npz: the zip central directory
    lives at the END of the file, so a torn write usually fails to
    open — and every member is read through so a truncated member
    behind an intact directory is caught HERE, at manifest selection
    (where falling back is cheap), not inside the strict restore."""
    with np.load(path) as data:
        if not data.files:
            raise ValueError(f"{path}: empty checkpoint archive")
        for key in data.files:
            data[key]


def latest_manifest(directory, counters=None):
    """The newest COMPLETE manifest under ``directory`` (or None).

    Complete = the manifest parses, carries the format tag, and every
    component file it names exists and passes the integrity probe.  A
    damaged newer manifest (host crash mid-commit, torn component) is
    counted (``ha_restore_fallbacks``) and warned, and the previous one
    is offered instead — never silent, never a half-cut."""
    for path in reversed(_manifest_paths(directory)):
        try:
            with open(path) as f:
                man = json.load(f)
            if man.get("format") != MANIFEST_FORMAT:
                raise ValueError(f"format {man.get('format')!r}")
            for key in ("train", "replay"):
                rel = man.get(key)
                if rel is None:
                    continue
                _verify_npz(os.path.join(directory, rel))
        except Exception as exc:  # noqa: BLE001 - fall back, loudly
            if counters is not None:
                counters.incr("ha_restore_fallbacks")
            logger.warning(
                "HA manifest %s is damaged (%s: %s); falling back to "
                "the previous one", path, type(exc).__name__, exc,
            )
            continue
        man["_path"] = path
        man["_directory"] = os.path.abspath(directory)
        return man
    return None


def restore_replay(manifest, shards=None, *, counters=None, timer=None,
                   fault_policy=None, timeoutms=5000, reconcile=True,
                   context=None):
    """Rebuild the replay buffer a manifest's cut describes.

    A ``sharded`` cut needs the shard endpoints (the same deployment,
    still running — the learner died, its storage tier did not) and
    restores with ``reconcile=True`` by default: shards legitimately
    sit AHEAD of the cut by whatever the dead learner appended after
    it, and exactly those slots leave the draw domain until the
    resumed actors rewrite them (docs/fault_tolerance.md).  A ``local``
    cut restores the in-process :class:`~blendjax.replay.ReplayBuffer`
    wholesale."""
    rel = manifest.get("replay")
    if rel is None:
        return None
    path = os.path.join(manifest["_directory"], rel)
    if manifest.get("replay_kind") == "sharded":
        if not shards:
            raise ValueError(
                "manifest describes a sharded replay cut; pass the "
                "shard endpoints to restore it"
            )
        from blendjax.replay.shard_client import ShardedReplay

        return ShardedReplay.restore(
            path, shards, counters=counters, timer=timer,
            fault_policy=fault_policy, timeoutms=timeoutms,
            context=context, reconcile=reconcile,
        )
    from blendjax.replay.buffer import ReplayBuffer

    return ReplayBuffer.restore(path, counters=counters, timer=timer)


class TrainCheckpointer:
    """Coordinated, atomic, versioned learner checkpoints (module doc).

    Params
    ------
    directory: str
        Checkpoint root.  Layout: ``train/step_<N>.npz`` (TrainState,
        via :class:`CheckpointManager`), ``replay_<N>.npz`` (the replay
        cut, when a buffer is attached), ``manifest_<N>.json`` (the
        commit record), ``learner_stats.json`` (the live stats mirror
        the supervisor's postmortem and the recovery benchmark read).
    every_updates: int
        Checkpoint cadence in completed learner updates.
    every_seconds: float | None
        Additional wall-clock cadence (whichever fires first).
    max_to_keep: int
        Retention depth, in complete cuts.
    stall_budget_s: float
        Budget for the synchronous barrier (host-gather + replay cut);
        exceeding it warns (debounced) — the knob is observability, the
        enforcement is the measured ``ha_snapshot`` stage and the
        ``ckpt_overhead_x`` benchmark floor.
    stats_path: str | None | "auto"
        Where :meth:`maybe_checkpoint` mirrors ``learner.stats()`` (an
        atomic small JSON, throttled): ``"auto"`` puts it in
        ``directory``; None disables.
    counters / timer:
        ``HA_EVENTS`` sink / ``HA_STAGES`` timer (process-wide
        defaults when omitted).
    """

    def __init__(self, directory, *, every_updates=50, every_seconds=None,
                 max_to_keep=3, stall_budget_s=1.0, stats_path="auto",
                 counters=None, timer=None):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.every_updates = max(1, int(every_updates))
        self.every_seconds = (
            None if every_seconds is None else float(every_seconds)
        )
        self.max_to_keep = max(1, int(max_to_keep))
        self.stall_budget_s = float(stall_budget_s)
        self.counters = counters if counters is not None else fleet_counters
        self.timer = timer if timer is not None else StageTimer()
        self.train_mgr = CheckpointManager(
            os.path.join(self.directory, "train"),
            max_to_keep=self.max_to_keep, counters=self.counters,
        )
        self.stats_path = (
            os.path.join(self.directory, "learner_stats.json")
            if stats_path == "auto" else stats_path
        )
        #: extra fields merged into every stats mirror (the learner
        #: child sets pid/resumed_from/probe info here once)
        self.stats_extra = {}
        self._lock = threading.Lock()
        self._inflight = None
        self._last_ckpt_update = 0
        self._last_ckpt_time = time.monotonic()
        self._last_stats_write = 0.0
        self._next_stall_warn = 0.0
        self._saves = 0
        self._skipped = 0
        self._failures = 0

    # -- cadence --------------------------------------------------------------

    def _due(self, updates):
        if updates - self._last_ckpt_update >= self.every_updates:
            return True
        return (
            self.every_seconds is not None
            and time.monotonic() - self._last_ckpt_time
            >= self.every_seconds
            and updates > self._last_ckpt_update
        )

    def maybe_checkpoint(self, learner):
        """The per-update hook (called by the learner thread once per
        completed update): mirrors the stats file (throttled) and takes
        a checkpoint when one is due and no serialization is already in
        flight.  Never raises into the update loop.  Returns the cut's
        update number when a checkpoint started, else None."""
        self._write_stats(learner)
        if not self._due(learner._updates_done):
            return None
        with self._lock:
            if self._inflight is not None and self._inflight.is_alive():
                self._skipped += 1
                self.counters.incr("ha_ckpt_skipped")
                return None
        return self._checkpoint(learner, block=False)

    def checkpoint(self, learner, block=True):
        """Force one coordinated checkpoint now.  ``block=True`` waits
        for the manifest commit (tests, clean shutdown); False matches
        :meth:`maybe_checkpoint`'s async behavior.  Returns the cut's
        update number, or None on failure (counted, logged)."""
        prev = self._inflight
        if prev is not None:
            prev.join()
        return self._checkpoint(learner, block=block)

    # -- the cut --------------------------------------------------------------

    def _checkpoint(self, learner, block):
        import jax

        t0 = time.perf_counter()
        try:
            # the synchronous barrier: host-gather the TrainState (the
            # _publish_params pattern — params AND optimizer state) and
            # take the replay cut under the buffer's own lock, so the
            # replay cursor and the learner step agree on one cut
            aux = learner.checkpoint_state()
            update = int(aux["updates"])
            host_state = jax.device_get(learner.state)
            replay_rel = replay_kind = None
            replay = learner.replay
            if replay is not None and hasattr(replay, "save"):
                replay_rel = f"replay_{update:08d}.npz"
                replay.save(os.path.join(self.directory, replay_rel))
                replay_kind = (
                    "sharded" if hasattr(replay, "num_shards")
                    else "local"
                )
        except Exception:  # noqa: BLE001 - training outlives checkpoints
            self._failures += 1
            self.counters.incr("ha_ckpt_failures")
            # advance the cadence cursors on FAILURE too (the serialize
            # path already does): the barrier is expensive — a host
            # gather plus a full-column checkpoint on every live shard
            # — and a persistent failure (ENOSPC is the canonical one)
            # must cost one attempt per cadence, not one per update
            self._last_ckpt_update = learner._updates_done
            self._last_ckpt_time = time.monotonic()
            logger.exception(
                "HA checkpoint barrier failed (training continues; the "
                "previous manifest keeps covering recovery; next "
                "attempt at the normal cadence)"
            )
            return None
        finally:
            dt = time.perf_counter() - t0
            self.timer.add("ha_snapshot", dt, _t0=t0)
        if dt > self.stall_budget_s:
            now = time.monotonic()
            if now >= self._next_stall_warn:
                self._next_stall_warn = now + 10.0
                logger.warning(
                    "HA checkpoint barrier took %.3fs (> stall budget "
                    "%.3fs) at update %d — the replay cut or the host "
                    "gather is outgrowing the budget; raise "
                    "every_updates or the budget", dt,
                    self.stall_budget_s, update,
                )
        self._last_ckpt_update = update
        self._last_ckpt_time = time.monotonic()
        if block:
            self._serialize(update, host_state, aux, replay_rel,
                            replay_kind)
            return update
        t = threading.Thread(
            target=self._serialize,
            args=(update, host_state, aux, replay_rel, replay_kind),
            daemon=True, name=f"bjx-ha-ckpt-{update}",
        )
        with self._lock:
            self._inflight = t
        t.start()
        return update

    def _serialize(self, update, host_state, aux, replay_rel,
                   replay_kind):
        """The background half: TrainState npz (fsync + atomic rename),
        manifest commit, retention.  Failures are counted, never
        raised — the previous manifest stays the recovery point."""
        t0 = time.perf_counter()
        try:
            train_path = self.train_mgr.save(update, host_state)
            train_rel = os.path.relpath(train_path, self.directory)
            nbytes = os.path.getsize(train_path)
            if replay_rel is not None:
                nbytes += os.path.getsize(
                    os.path.join(self.directory, replay_rel)
                )
            manifest = {
                "format": MANIFEST_FORMAT,
                "update": update,
                "ts": time.time(),
                "train": train_rel,
                "replay": replay_rel,
                "replay_kind": replay_kind,
                "aux": aux,
            }
            _write_json_durable(
                os.path.join(self.directory,
                             f"manifest_{update:08d}.json"),
                manifest,
            )
            self._retain()
            self._saves += 1
            self.counters.incr("ha_ckpt_saves")
            self.counters.incr("ha_ckpt_bytes", int(nbytes))
        except Exception:  # noqa: BLE001 - see docstring
            self._failures += 1
            self.counters.incr("ha_ckpt_failures")
            logger.exception(
                "HA checkpoint serialization failed at update %d "
                "(training continues; the previous manifest keeps "
                "covering recovery)", update,
            )
        finally:
            self.timer.add("ha_serialize", time.perf_counter() - t0,
                           _t0=t0)

    def _retain(self):
        paths = _manifest_paths(self.directory)
        for path in paths[:max(0, len(paths) - self.max_to_keep)]:
            try:
                with open(path) as f:
                    man = json.load(f)
            except Exception:  # noqa: BLE001 - damaged manifest
                man = {}
            for key in ("replay",):
                rel = man.get(key)
                if rel:
                    try:
                        os.unlink(os.path.join(self.directory, rel))
                    except OSError:
                        pass
            try:
                os.unlink(path)
            except OSError:
                continue
            self.counters.incr("ha_ckpt_evicted")
        # train steps retire through the CheckpointManager's own
        # retention (same depth, pruned at each save)

    # -- restore --------------------------------------------------------------

    def latest_manifest(self):
        return latest_manifest(self.directory, counters=self.counters)

    def restore(self, learner, manifest=None, *, republish=True):
        """Resume ``learner`` from a manifest (default: the latest
        complete one; raises FileNotFoundError when none exists).

        Applies the TrainState (strictly the manifest's step — the cut
        is all-or-nothing; damaged cuts were already skipped by
        :func:`latest_manifest`), the update counter / curriculum /
        scenario assignments via :meth:`ActorLearner.
        load_checkpoint_state`, and — when the learner carries a weight
        bus and ``republish`` — publishes the restored params under a
        fresh HIGHER version id (``ha_resume_publishes``): the serve
        tier rolls forward across the respawn, subscribers heal through
        their periodic re-sync, and clients observe a monotonic version
        stream with zero errors.  Returns the manifest."""
        import jax

        if manifest is None:
            manifest = self.latest_manifest()
            if manifest is None:
                raise FileNotFoundError(
                    f"no complete HA manifest under {self.directory}"
                )
        t0 = time.perf_counter()
        state = self.train_mgr.restore(
            learner.state, step=int(manifest["update"])
        )
        learner.load_checkpoint_state(state, manifest.get("aux") or {})
        self._last_ckpt_update = int(manifest["update"])
        self._last_ckpt_time = time.monotonic()
        self.counters.incr("ha_restores")
        self.timer.add("ha_restore", time.perf_counter() - t0, _t0=t0)
        flight_recorder.note(
            "learner_restored", target="learner",
            update=int(manifest["update"]),
            manifest=manifest.get("_path"),
        )
        if republish and learner.weight_bus is not None:
            v = learner.weight_bus.publish(
                jax.device_get(learner.state.params),
                step=learner._updates_done,
            )
            learner.last_published_version = v
            self.counters.incr("ha_resume_publishes")
            logger.info(
                "resume republish: checkpointed params (update %d) "
                "published as weight version %s — the serve tier rolls "
                "forward", learner._updates_done, v,
            )
        return manifest

    # -- observability --------------------------------------------------------

    def join(self, timeout=None):
        """Wait for an in-flight background serialization (tests /
        clean shutdown)."""
        t = self._inflight
        if t is not None:
            t.join(timeout)

    def _write_stats(self, learner, force=False):
        if self.stats_path is None:
            return
        now = time.monotonic()
        if not force and now - self._last_stats_write < 0.2:
            return
        self._last_stats_write = now
        try:
            doc = {
                "ts": time.time(),
                "pid": os.getpid(),
                "updates": learner._updates_done,
                "last_published_version": learner.last_published_version,
                "last_ckpt_update": self._last_ckpt_update,
            }
            try:
                doc["stats"] = learner.stats()
            except Exception:  # noqa: BLE001 - mirror must not cascade
                pass
            doc.update(self.stats_extra)
            tmp = f"{self.stats_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f, default=repr)
            os.replace(tmp, self.stats_path)
        except Exception:  # noqa: BLE001 - mirror must not cascade
            logger.exception("HA stats mirror write failed")

    def stats(self):
        with self._lock:
            inflight = (
                self._inflight is not None and self._inflight.is_alive()
            )
        return {
            "directory": self.directory,
            "every_updates": self.every_updates,
            "every_seconds": self.every_seconds,
            "max_to_keep": self.max_to_keep,
            "saves": self._saves,
            "skipped": self._skipped,
            "failures": self._failures,
            "last_ckpt_update": self._last_ckpt_update,
            "manifests": len(_manifest_paths(self.directory)),
            "serialize_inflight": inflight,
        }
