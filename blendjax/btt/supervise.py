"""Supervised restart-and-resync: the fleet's self-healing control loop.

:class:`blendjax.btt.watchdog.FleetWatchdog` respawns dead producers, and
:class:`blendjax.btt.envpool.EnvPool` quarantines/re-admits unresponsive
envs — but the reference architecture (and PR 1's port of it) left those
two halves unconnected: a respawned producer sat idle until the consumer
happened to time out into it.  ``FleetSupervisor`` closes the loop:

- on producer **death** it immediately quarantines the matching pool env
  (no waiting for an RPC timeout into a dead peer) and counts the event;
- on **respawn** it clears that env's backoff/circuit state and drives
  the re-admission handshake from its own heal thread, so envs rejoin
  within the fault policy's deadline even when the training loop is busy;
- **dataset streams** need no RPC resync (tcp consumers keep their
  connect-mode sockets; shm readers remap the new ring generation via the
  rc -4 reopen path in :mod:`blendjax.native.ring`), but the supervisor
  verifies the remap through registered health checks and reports it;
- :meth:`health` snapshots the whole story — deaths, restarts, retries,
  quarantines, timeouts, re-admissions, circuit trips, stream timeouts,
  TransferGate backstop fires — from the shared
  :class:`blendjax.utils.timing.EventCounters`.

Usage::

    counters = EventCounters()
    pool = EnvPool(addresses, fault_policy=policy, counters=counters)
    with FleetSupervisor(launcher, pool=pool, interval=0.5) as sup:
        for step in range(n):
            obs, rew, done, infos = pool.step(actions)   # N-1 under faults
        assert sup.health()["quarantines"] == 0          # clean run
"""

from __future__ import annotations

import logging
import threading
import time

from blendjax.btt.watchdog import FleetWatchdog
from blendjax.obs.flight import default_postmortem_dir, flight_recorder
from blendjax.obs.histogram import fold_stage_snapshot, stage_records
from blendjax.utils.timing import FLEET_EVENTS, REPLAY_EVENTS, fleet_counters

logger = logging.getLogger("blendjax")


class FleetSupervisor:
    """Ties fleet restarts to consumer healing, with one health surface.

    Params
    ------
    launcher: BlenderLauncher
        A launcher inside its context (``launch_info`` populated).
    pool: EnvPool | None
        Pool to quarantine/re-admit in lockstep with producer deaths.
        Instance ``i`` of the launcher must serve env ``i`` of the pool
        (the natural outcome of building the pool from
        ``launch_info.addresses``).
    interval: float
        Watchdog poll period, seconds.
    restart: bool
        Respawn dead producers (off = detect/quarantine only).
    counters: EventCounters | None
        Event sink; defaults to the pool's counters (so pool-side retry/
        quarantine events and supervisor-side death/restart events land
        in one snapshot), else the process-wide ``fleet_counters``.
    on_death: callable | None
        Extra ``on_death(index, exit_code)`` user hook, invoked after the
        supervisor's own handling.
    heal_interval: float
        Heal-thread cadence, seconds (each tick drives pending
        re-admission probes).
    replay: blendjax.replay.ReplayBuffer | ShardedReplay | None
        When the training loop runs off-policy, attach its buffer (here
        or via :meth:`attach_replay`) so :meth:`health` reports the
        replay fill/exclusion state and stage timings alongside the
        fleet counters — one snapshot for the whole acting+learning
        story.  A :class:`~blendjax.replay.ShardedReplay` is supervised
        like a fleet: when this supervisor's launcher IS the shard
        fleet (:class:`~blendjax.replay.service.ShardFleet`, pool
        None), a shard-process death quarantines the matching shard
        proactively and a respawn clears its backoff state; either way
        the heal thread drives :meth:`ShardedReplay.probe` so restored
        shards re-admit within the policy deadline.
    fleet_id: int | None
        This fleet's index in a multi-fleet (Sebulba) deployment — the
        breakdown key :func:`aggregate_health` reports per-fleet
        counters under.  Give each fleet's supervisor its OWN
        ``EventCounters`` so the per-fleet slices stay disjoint
        (:class:`blendjax.parallel.podracer.FleetSet` does).
    timer: StageTimer | None
        Attach the fleet's stage timer (the one its feed/replay path
        records into) so :meth:`health` reports per-stage latency
        percentiles (``stages``) next to the counters, and
        :func:`aggregate_health` can merge the histograms across
        fleets.
    hub: blendjax.obs.TelemetryHub | None
        Register this supervisor's counters/timer/health with a
        telemetry hub at construction (name ``fleet<id>``), so one
        ``hub.scrape()`` covers the fleet without extra plumbing.
    postmortem_dir: str | None
        Where to dump a flight-recorder postmortem JSON when a producer
        (or supervised shard process) dies — the crash artifact naming
        the quarantined target.  Defaults to ``$BJX_POSTMORTEM_DIR``
        (set by ``make chaos``/``make chaos-replay``); with neither
        set, deaths are still recorded in the process-wide flight ring
        but no file is written.
    """

    def __init__(
        self,
        launcher,
        pool=None,
        interval=1.0,
        restart=True,
        counters=None,
        on_death=None,
        heal_interval=0.05,
        replay=None,
        fleet_id=None,
        timer=None,
        hub=None,
        postmortem_dir=None,
    ):
        self.launcher = launcher
        self.pool = pool
        self.fleet_id = fleet_id
        self.timer = timer
        self.postmortem_dir = (
            postmortem_dir if postmortem_dir is not None
            else default_postmortem_dir()
        )
        #: path of the most recent postmortem dump (None until a death)
        self.last_postmortem = None
        if counters is None:
            counters = pool.counters if pool is not None else fleet_counters
        self.counters = counters
        if hub is not None:
            hub.register_supervisor(
                f"fleet{fleet_id if fleet_id is not None else 0}", self
            )
        self._user_on_death = on_death
        self.watchdog = FleetWatchdog(
            launcher, interval=interval, on_death=self._on_death,
            restart=restart,
        )
        self.replay = replay
        self.heal_interval = heal_interval
        self._stop = threading.Event()
        self._event = threading.Event()  # pulses on any state change
        self._heal_thread = None
        self._checks = {}
        self._down = set()  # instances reported dead, respawn still owed

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        if self._heal_thread is not None:
            raise RuntimeError("supervisor already started")
        self.watchdog.start()
        self._heal_thread = threading.Thread(
            target=self._heal_loop, daemon=True, name="bjx-supervisor"
        )
        self._heal_thread.start()
        return self

    def stop(self):
        self._stop.set()
        self.watchdog.stop()
        if self._heal_thread is not None:
            self._heal_thread.join(timeout=self.heal_interval + 5)
            self._heal_thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- death -> quarantine -> resync --------------------------------------

    def _on_death(self, idx, code):
        # the watchdog reports a death with a FAILED respawn once, then
        # re-fires when a later respawn succeeds; distinguish via its own
        # death log (the callback runs synchronously after the append) so
        # deaths count physical deaths and restarts count real respawns
        rec = next(
            (d for d in reversed(self.watchdog.deaths) if d[0] == idx), None
        )
        target = (
            f"fleet{self.fleet_id}/instance{idx}"
            if self.fleet_id is not None else f"instance{idx}"
        )
        respawned = bool(rec and rec[2])
        new_death = not (respawned and idx in self._down)
        if not new_death:
            self._down.discard(idx)  # same death, respawn finally landed
        else:
            self.counters.incr("deaths")
            flight_recorder.note(
                "producer_death", target=target,
                exit_code=code, respawned=respawned,
            )
        if self.pool is not None and idx < self.pool.num_envs:
            # proactive: stop RPCing a peer known to be dead instead of
            # discovering it one timeout at a time
            self.pool.quarantine_env(
                idx, reason=f"producer died (exit {code})"
            )
        # a supervisor whose launcher is the replay shard fleet (pool
        # None) maps instance deaths onto shard quarantine the same way
        # an env supervisor maps them onto pool quarantine
        rep = self.replay
        rep_is_sharded = (
            rep is not None and self.pool is None
            and hasattr(rep, "quarantine_shard")
            and idx < getattr(rep, "num_shards", 0)
        )
        if rep_is_sharded:
            rep.quarantine_shard(
                idx, reason=f"shard process died (exit {code})"
            )
        if respawned:
            self.counters.incr("restarts")
            if self.pool is not None and idx < self.pool.num_envs:
                # the endpoint is coming back: drop backoff/circuit state
                # so the heal loop re-dials it immediately
                self.pool.notify_respawn(idx)
            if rep_is_sharded:
                rep.notify_respawn(idx)
        elif self.watchdog.restart:
            self._down.add(idx)  # respawn failed; watchdog retries it
        if new_death and self.postmortem_dir is not None:
            # AFTER the quarantines above, so the dump's event ring ends
            # with what was done about the death, and its health snapshot
            # reflects the degraded state being entered
            try:
                extra = {"target": target, "exit_code": code,
                         "health": self.health()}
            except Exception:  # noqa: BLE001 - dump must not cascade
                extra = {"target": target, "exit_code": code}
            self.last_postmortem = flight_recorder.dump(
                directory=self.postmortem_dir,
                reason=f"death-{target}",
                extra=extra,
            )
        self._event.set()
        if self._user_on_death is not None:
            self._user_on_death(idx, code)

    def _heal_loop(self):
        while not self._stop.wait(self.heal_interval):
            pool = self.pool
            try:
                if pool is not None and pool.quarantined.any() \
                        and pool.probe(block_ms=20):
                    self._event.set()
            except Exception:
                # the heal loop shares the watchdog's prime directive:
                # it must outlive whatever it is healing
                logger.exception("supervisor heal tick failed")
            rep = self.replay
            if rep is None or not hasattr(rep, "probe"):
                continue
            try:
                quarantined = getattr(rep, "quarantined", None)
                if quarantined is not None and quarantined.any() \
                        and rep.probe(block_ms=20):
                    self._event.set()
            except Exception:
                logger.exception("supervisor replay heal tick failed")

    # -- stream verification --------------------------------------------------

    def attach_replay(self, buffer):
        """Report ``buffer`` (a :class:`blendjax.replay.ReplayBuffer`)
        in :meth:`health` snapshots — same effect as the constructor's
        ``replay=``, for buffers created after the supervisor."""
        self.replay = buffer

    def add_health_check(self, name, fn):
        """Register ``fn() -> bool`` evaluated by :meth:`health` and
        required by :meth:`await_healthy` — e.g. a dataset-stream remap
        probe (``lambda: reader.reconnects >= 1`` for the shm rc -4 path,
        or a freshness check on the consuming iterator)."""
        self._checks[name] = fn

    # -- observability ------------------------------------------------------

    def health(self):
        """One snapshot of fleet health: every canonical fault counter
        (zero-filled, see ``FLEET_EVENTS``/``REPLAY_EVENTS``), watchdog
        liveness, the pool's quarantine state, the attached replay
        buffer's fill/exclusion stats, and registered stream checks."""
        h = dict.fromkeys(FLEET_EVENTS + REPLAY_EVENTS, 0)
        h.update(self.counters.snapshot())
        h["alive"] = self.watchdog.alive
        if self.fleet_id is not None:
            h["fleet_id"] = self.fleet_id
        if self.timer is not None:
            # per-stage means AND latency percentiles (p50/p90/p99/max)
            # from the attached StageTimer's histograms
            h["stages"] = self.timer.summary()
        if self.pool is not None:
            mask = self.pool.healthy
            h["num_envs"] = int(mask.size)
            h["healthy_envs"] = int(mask.sum())
            # async pipeline observability: how deep each env's in-flight
            # queue currently is vs. the configured ceiling
            depths = getattr(self.pool, "inflight", None)
            if depths is not None:
                h["inflight_per_env"] = list(depths)
                h["inflight_total"] = int(sum(depths))
                h["pipeline_depth"] = int(
                    getattr(self.pool, "pipeline_depth", 1)
                )
        if self.replay is not None:
            h["replay"] = self.replay.stats()
        h["checks"] = {name: bool(fn()) for name, fn in self._checks.items()}
        return h

    def _await(self, cond, timeout):
        """Bounded wait for ``cond()`` — event-pulsed, no bare sleeps."""
        deadline = time.monotonic() + timeout
        while True:
            if cond():
                return True
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            self._event.clear()
            self._event.wait(min(0.05, remaining))

    def await_deaths(self, n=1, timeout=30.0):
        """Block until ``n`` producer deaths have been processed (their
        envs quarantined, respawns issued).  True on success."""
        return self._await(lambda: self.counters.get("deaths") >= n, timeout)

    def await_healthy(self, timeout=30.0):
        """Block until every pool env is healthy, every replay shard is
        re-admitted (when the attached replay is sharded), and every
        registered check passes.  True on success, False on timeout."""

        def cond():
            if self.pool is not None and not self.pool.healthy.all():
                return False
            rep_q = getattr(self.replay, "quarantined", None)
            if rep_q is not None and rep_q.any():
                return False
            return all(bool(fn()) for fn in self._checks.values())

        return self._await(cond, timeout)


def aggregate_health(supervisors):
    """One health snapshot over a multi-fleet (Sebulba) deployment.

    Every canonical counter (``FLEET_EVENTS`` + ``REPLAY_EVENTS``) is
    summed across the fleets' supervisors — the quarantine/death/retry
    totals the sharded bench surfaces — and each fleet's full
    :meth:`FleetSupervisor.health` snapshot rides underneath, keyed by
    its ``fleet_id`` (the per-fleet breakdown: the ``fleet_id``
    dimension on the shared event vocabulary).  ``num_envs`` /
    ``healthy_envs`` sum across fleets; ``alive`` is True only while
    EVERY fleet's watchdog is alive; ``dead_fleets`` lists fleets whose
    pool has no healthy env left (the mask a sharded learner zeroes —
    see :class:`blendjax.parallel.podracer.SegmentFanIn`).
    """
    agg = dict.fromkeys(FLEET_EVENTS + REPLAY_EVENTS, 0)
    fleets = {}
    num_envs = healthy_envs = 0
    alive = True
    dead_fleets = []
    stage_merge = {}  # the obs.histogram.fold_stage_snapshot accumulator
    for idx, sup in enumerate(supervisors):
        h = sup.health()
        fid = h.get("fleet_id", idx)
        fleets[fid] = h
        for name in FLEET_EVENTS + REPLAY_EVENTS:
            agg[name] += int(h.get(name, 0))
        num_envs += int(h.get("num_envs", 0))
        healthy_envs += int(h.get("healthy_envs", 0))
        alive = alive and bool(h.get("alive", False))
        if h.get("num_envs", 0) and h.get("healthy_envs", 0) == 0:
            dead_fleets.append(fid)
        timer = getattr(sup, "timer", None)
        if timer is not None:
            fold_stage_snapshot(stage_merge, timer.snapshot())
    agg.update(
        num_fleets=len(fleets),
        num_envs=num_envs,
        healthy_envs=healthy_envs,
        alive=alive,
        dead_fleets=dead_fleets,
        fleets=fleets,
    )
    if stage_merge:
        # cross-fleet stage latencies: histograms merged so the
        # aggregate p99 is a quantile of the UNION of intervals, not a
        # mean of per-fleet percentiles
        agg["stages"] = stage_records(stage_merge)
    return agg
