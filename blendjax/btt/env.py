"""Consumer-side remote environment (reference ``btt/env.py:7-316``).

``RemoteEnv`` gives the familiar blocking ``step()/reset()`` over a REQ
socket whose peer is a :class:`blendjax.btb.env.RemoteControlledAgent`
inside Blender.  One ``step()`` == one simulated frame.  Observations come
back as numpy-friendly pytrees, ready for ``jax.device_put`` — for batched
policy training over many instances use :class:`blendjax.btt.envpool.EnvPool`.

``REQ_RELAXED`` + ``REQ_CORRELATE`` keep the REQ socket usable after a
timeout (no strict alternation lockup), matching the reference
(``btt/env.py:40-41``).
"""

from __future__ import annotations

from contextlib import ExitStack, contextmanager

import zmq

from blendjax import wire
from blendjax.btt.constants import DEFAULT_TIMEOUTMS


class RemoteEnv:
    """Blocking client for one remote Blender environment.

    ``fault_policy`` (a :class:`blendjax.btt.faults.FaultPolicy`) makes
    every RPC retry with backoff inside the policy's deadline and trips a
    circuit breaker after consecutive failures; without one, a single
    timeout raises (the reference behavior).  Retries re-send the request
    under the same correlation id, which blendjax producers dedupe (the
    frame is never simulated twice) — see :mod:`blendjax.btt.faults` for
    the caveat with producers that ignore the id.
    """

    def __init__(self, address, timeoutms=DEFAULT_TIMEOUTMS, fault_policy=None,
                 counters=None):
        self._ctx = zmq.Context.instance()
        self.socket = self._ctx.socket(zmq.REQ)
        self.socket.setsockopt(zmq.LINGER, 0)
        self.socket.setsockopt(zmq.SNDTIMEO, timeoutms * 10)
        self.socket.setsockopt(zmq.RCVTIMEO, timeoutms)
        self.socket.setsockopt(zmq.REQ_RELAXED, 1)
        self.socket.setsockopt(zmq.REQ_CORRELATE, 1)
        self.socket.connect(address)
        self.env_time = None
        self.rgb_array = None
        self.viewer = None
        self.fault_policy = fault_policy
        self._fault_state = (
            fault_policy.new_state() if fault_policy is not None else None
        )
        self._counters = counters

    def reset(self):
        """Reset; returns ``(obs, info)`` (reference ``btt/env.py:47-60``)."""
        ddict = self._reqrep(cmd="reset")
        self.rgb_array = ddict.pop("rgb_array", None)
        return ddict.pop("obs"), ddict

    def step(self, action):
        """Apply ``action``; returns ``(obs, reward, done, info)``.

        ``action`` must be wire-serializable (numbers, numpy arrays,
        nested containers thereof).
        """
        ddict = self._reqrep(cmd="step", action=action)
        obs = ddict.pop("obs")
        reward = ddict.pop("reward")
        done = ddict.pop("done")
        self.rgb_array = ddict.pop("rgb_array", None)
        return obs, reward, done, ddict

    def render(self, mode="human", backend=None):
        """Show (or return) the last frame rendered by the remote env's
        attached renderer (reference ``btt/env.py:88-109``)."""
        if mode == "rgb_array" or self.rgb_array is None:
            return self.rgb_array
        if self.viewer is None:
            from blendjax.btt.env_rendering import create_renderer

            self.viewer = create_renderer(backend)
        self.viewer.imshow(self.rgb_array)
        return None

    def _reqrep(self, **send_kwargs):
        if self.fault_policy is None:
            return self._attempt(send_kwargs)
        # one correlation id for every re-send of this logical call: the
        # producer-side agent dedupes a retried non-idempotent ``step``
        # (serving its cached reply instead of simulating the frame twice)
        wire.stamp_message_id(send_kwargs)
        return self.fault_policy.run(
            lambda attempt: self._attempt(send_kwargs),
            state=self._fault_state,
            counters=self._counters,
            name=f"RemoteEnv {send_kwargs.get('cmd', 'rpc')}",
        )

    def _attempt(self, send_kwargs):
        """One send+recv cycle (REQ_RELAXED keeps the socket usable for a
        policy-driven re-send after a timeout)."""
        try:
            wire.send_message(self.socket, {**send_kwargs, "time": self.env_time})
        except zmq.Again:
            raise TimeoutError("Failed to send to remote environment") from None
        try:
            ddict = wire.recv_message(self.socket)
        except zmq.Again:
            raise TimeoutError("No response from remote environment") from None
        ddict.pop(wire.BTMID_KEY, None)  # echoed correlation id, not info
        self.env_time = ddict["time"]
        return ddict

    def close(self):
        if self.viewer is not None:
            self.viewer.close()
            self.viewer = None
        if self.socket is not None:
            self.socket.close(0)
            self.socket = None


def kwargs_to_cli(kwargs):
    """Python kwargs -> CLI flags for the remote env script: ``k=v`` becomes
    ``--k v``; booleans become ``--k`` / ``--no-k``; underscores become
    dashes (reference ``btt/env.py:162-173``)."""
    args = []
    for key, value in kwargs.items():
        key = key.replace("_", "-")
        if isinstance(value, bool):
            args.append(f"--{key}" if value else f"--no-{key}")
        else:
            args.extend([f"--{key}", str(value)])
    return args


@contextmanager
def launch_env(scene, script, background=False, timeoutms=DEFAULT_TIMEOUTMS,
               fault_policy=None, **kwargs):
    """Launch one Blender env instance and yield a connected RemoteEnv
    (reference ``btt/env.py:136-189``).  Extra kwargs become CLI flags for
    the env script (see :func:`kwargs_to_cli`)."""
    from blendjax.btt.launcher import BlenderLauncher

    env = None
    try:
        with BlenderLauncher(
            scene=scene,
            script=script,
            num_instances=1,
            named_sockets=["GYM"],
            instance_args=[kwargs_to_cli(kwargs)],
            background=background,
        ) as bl:
            env = RemoteEnv(bl.launch_info.addresses["GYM"][0],
                            timeoutms=timeoutms, fault_policy=fault_policy)
            yield env
    finally:
        if env is not None:
            env.close()


def _gym_module():
    try:
        import gymnasium

        return gymnasium
    except ImportError:
        pass
    try:
        import gym

        return gym
    except ImportError:
        return None


_gym = _gym_module()

#: True when the adapter's backing module is gymnasium, whose API differs
#: from classic gym: ``step`` returns a 5-tuple with separate
#: ``terminated``/``truncated`` flags and ``reset`` returns ``(obs, info)``.
USING_GYMNASIUM = _gym is not None and _gym.__name__ == "gymnasium"


def adapt_step_result(obs, reward, done, info, gymnasium_api):
    """Convert the wire-level ``(obs, reward, done, info)`` to the backing
    module's ``step`` contract.

    Under gymnasium: ``(obs, reward, terminated, truncated, info)``.  The
    producer's ``done`` means task termination (e.g. the pole fell); the
    remote protocol has no separate time-limit signal, so ``truncated`` is
    always False — wrap with ``gymnasium.wrappers.TimeLimit`` for episode
    caps.  Under classic gym: the legacy 4-tuple, unchanged."""
    if gymnasium_api:
        return obs, reward, bool(done), False, info
    return obs, reward, done, info


if _gym is not None:

    class OpenAIRemoteEnv(_gym.Env):
        """gym/gymnasium adapter over :func:`launch_env`
        (reference ``btt/env.py:195-313``).  Subclass, call
        :meth:`launch` with your scene/script, and register with gym.

        The adapter follows whichever module backs it: under gymnasium,
        ``step`` returns the 5-tuple ``(obs, reward, terminated,
        truncated, info)`` and ``reset`` returns ``(obs, info)``; under
        classic gym, the legacy 4-tuple and bare-obs reset."""

        metadata = {
            "render.modes": ["rgb_array", "human"],  # classic gym key
            "render_modes": ["rgb_array", "human"],
        }

        def __init__(self, version="0.0.1"):
            self.__version__ = version
            self._es = ExitStack()
            self._env = None

        def launch(self, scene, script, background=False, **kwargs):
            if self._env is not None:
                raise RuntimeError("Environment already running.")
            self._env = self._es.enter_context(
                launch_env(scene=scene, script=script, background=background, **kwargs)
            )

        def step(self, action):
            obs, reward, done, info = self._env.step(action)
            return adapt_step_result(obs, reward, done, info, USING_GYMNASIUM)

        def reset(self, *, seed=None, options=None):
            if USING_GYMNASIUM:
                # seeds the np_random generator per the gymnasium contract;
                # the remote scene's randomization is seeded at launch
                # (-btseed), so a mid-run seed only affects local sampling
                super().reset(seed=seed)
                obs, info = self._env.reset()
                return obs, info
            obs, _ = self._env.reset()
            return obs

        def render(self, mode="human"):
            return self._env.render(mode=mode)

        @property
        def env_time(self):
            return self._env.env_time

        def close(self):
            if self._es is not None:
                self._es.close()
                self._es = None
                self._env = None

        def __del__(self):
            self.close()

else:  # pragma: no cover - gym not installed

    class OpenAIRemoteEnv:  # noqa: D401 - stub
        """Placeholder raising on use: neither gym nor gymnasium installed."""

        def __init__(self, *a, **k):
            raise ImportError(
                "OpenAIRemoteEnv requires gym or gymnasium; "
                "use RemoteEnv / EnvPool for the jax-native interface."
            )
