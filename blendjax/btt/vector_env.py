"""gymnasium ``VectorEnv`` adapter over :class:`blendjax.btt.envpool.EnvPool`.

The reference exposes single environments through the classic gym API
(``pkg_pytorch/blendtorch/btt/env.py:195-313``); its fleet story stops at
N independent envs.  blendjax's ``EnvPool`` already steps a whole Blender
fleet in pipelined lockstep; this module makes that fleet a drop-in
``gymnasium.vector.VectorEnv`` so vectorized agent libraries (CleanRL-
style PPO loops, SB3 VecEnv consumers via shims, ...) can drive Blender
fleets unchanged.

Autoreset follows gymnasium's NEXT_STEP mode, which is exactly
``EnvPool``'s native behavior: a terminated instance returns its terminal
observation with ``terminations[i] = True``; the reset happens on the
*next* ``step`` call, which returns the fresh observation with zero
reward.

The gymnasium ``step_async``/``step_wait`` pair is implemented over the
pool's pipelined DEALER path (docs/rl_stepping.md): between the two
calls the whole fleet is simulating its next frame, so vectorized
trainers that compute anything in that window (advantage math, buffer
writes, logging) get it for free.  ``step`` remains the lock-step
REQ/REP path.
"""

from __future__ import annotations

import numpy as np

try:
    import gymnasium as _gym
    from gymnasium.vector.utils import batch_space as _batch_space
except ImportError:  # pragma: no cover - gymnasium is an optional dep
    _gym = None


def _require_gymnasium():
    if _gym is None:
        raise ImportError(
            "gymnasium is required for BlenderVectorEnv; pip install gymnasium"
        )


if _gym is not None:

    class BlenderVectorEnv(_gym.vector.VectorEnv):
        """A fleet of remote Blender environments as one vector env.

        Params
        ------
        pool: EnvPool
            Connected pool (see :func:`blendjax.btt.envpool.launch_env_pool`).
            The adapter owns it: ``close()`` closes the pool.
        single_observation_space / single_action_space: gymnasium.Space
            Per-instance spaces (the wire protocol is schema-free, so the
            caller declares them, exactly like the reference's
            ``OpenAIRemoteEnv`` subclasses do).
        """

        metadata = {"autoreset_mode": (
            _gym.vector.AutoresetMode.NEXT_STEP
            if hasattr(_gym.vector, "AutoresetMode") else "next_step"
        )}

        def __init__(self, pool, single_observation_space,
                     single_action_space):
            if not getattr(pool, "autoreset", False):
                raise ValueError(
                    "BlenderVectorEnv advertises NEXT_STEP autoreset and "
                    "requires an EnvPool built with autoreset=True"
                )
            self._pool = pool
            self.num_envs = pool.num_envs
            self.single_observation_space = single_observation_space
            self.single_action_space = single_action_space
            self.observation_space = _batch_space(
                single_observation_space, pool.num_envs
            )
            self.action_space = _batch_space(
                single_action_space, pool.num_envs
            )

        @staticmethod
        def _as_batched(obs):
            # collate() returns a dict/tuple pytree for structured
            # observations (Dict/Tuple spaces): leave those alone —
            # np.asarray would collapse them to a 0-d object array
            if isinstance(obs, (dict, tuple, list)):
                return obs
            return np.asarray(obs)

        def reset(self, *, seed=None, options=None):
            # remote scenes seed at launch (-btseed); a per-reset seed has
            # no remote hook, mirroring the reference's OpenAIRemoteEnv
            obs, infos = self._pool.reset()
            return self._as_batched(obs), {"env_infos": infos}

        @staticmethod
        def _route_dones(obs, rewards, dones, infos):
            dones = np.asarray(dones, dtype=bool)
            # a quarantine done is an episode cut short (producer died /
            # hung), not a task-terminal state: gymnasium-conformant
            # trainers must keep bootstrapping V(s') there, so it routes
            # to truncations, never terminations
            truncations = np.array(
                [bool(info.get("quarantined")) for info in infos], dtype=bool
            ) & dones
            terminations = dones & ~truncations
            return (
                BlenderVectorEnv._as_batched(obs),
                rewards,
                terminations,
                truncations,
                {"env_infos": infos},
            )

        def step(self, actions):
            return self._route_dones(*self._pool.step(list(actions)))

        def step_async(self, actions):
            """Submit the batch without waiting (gymnasium vector pair).

            The fleet simulates while the caller computes; collect with
            :meth:`step_wait`.  Backed by ``EnvPool.step_async`` — the
            producers integrate physics for frame t+1 concurrently with
            whatever runs between the two calls.
            """
            self._pool.step_async(list(actions))

        def step_wait(self):
            """Collect the batch submitted by :meth:`step_async`; same
            5-tuple (and autoreset/truncation routing) as :meth:`step`."""
            return self._route_dones(*self._pool.step_wait_full())

        def close_extras(self, **kwargs):
            self._pool.close()

else:  # pragma: no cover

    class BlenderVectorEnv:  # noqa: D401 - stub keeps imports harmless
        """Unavailable: gymnasium is not installed."""

        def __init__(self, *a, **k):
            _require_gymnasium()


def launch_vector_env(scene, script, num_instances, single_observation_space,
                      single_action_space, **kwargs):
    """Launch a Blender fleet and wrap it as a gymnasium ``VectorEnv``.

    Context manager; extra kwargs flow to
    :func:`blendjax.btt.envpool.launch_env_pool` (and on to each
    instance's CLI).
    """
    _require_gymnasium()
    from contextlib import contextmanager

    from blendjax.btt.envpool import launch_env_pool

    @contextmanager
    def _cm():
        with launch_env_pool(
            scene=scene, script=script, num_instances=num_instances, **kwargs
        ) as pool:
            env = BlenderVectorEnv(
                pool, single_observation_space, single_action_space
            )
            try:
                yield env
            finally:
                env.close()

    return _cm()
