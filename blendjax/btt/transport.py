"""Transport selection for the RPC clients: ZMQ always, shm when it can.

:class:`RpcChannel` is what :class:`~blendjax.replay.shard_client.
ShardClient`, :class:`~blendjax.serve.client.ServeClient` and the
gateway's replica backends dial through instead of a bare DEALER
socket.  It speaks the channel protocol
(:func:`blendjax.btt.rpc.exactly_once_rpc` consumes it):

- ``send_request(msg, raw_buffers)`` — encode and send one request;
- ``poll_reply(ms)`` / ``recv_reply()`` — bounded wait / one decoded
  reply (None when the wakeup was spurious);
- ``notify_timeout()`` — the attempt deadline expired (the demote
  signal for a dead shm peer: the fault-policy retry then rides ZMQ).

Selection is automatic and conservative:

1. every channel starts on ZMQ (which stays the control plane and the
   remote-peer path);
2. once the peer has proven alive (a reply arrived) and the channel has
   carried ``upgrade_after`` RPCs (probe clients that do one ``hello``
   and hang up never pay the negotiation), the client attempts the
   shm upgrade: two uncounted control RPCs (``shm_connect`` /
   ``shm_attach``, see :mod:`blendjax.btt.shm_rpc`) negotiate a ring
   pair and from then on requests/replies move through shared memory;
3. a server that refuses (kill-switch, different host, pre-ShmRPC
   build) turns the upgrade off for the channel's lifetime; transient
   failures back off and retry after the next healthy ZMQ reply;
4. any shm failure mid-flight — vanished ring (server respawned),
   reply timeout, full request ring — **demotes** the channel back to
   ZMQ on the spot.  The in-flight retry rides the same correlation id
   over ZMQ exactly as it does over TCP today, and the channel
   re-upgrades onto a fresh ring generation once the (respawned) server
   answers again: the ``ShmRingReader.auto_reopen`` generation-remap
   pattern, driven from the RPC layer.

``BJX_NO_SHM_RPC=1`` (or ``shm=False``) pins the channel to ZMQ —
byte-identical behavior to the pre-ShmRPC client.
"""

from __future__ import annotations

import logging
import time

from blendjax import wire
from blendjax.btt import shm_rpc

logger = logging.getLogger("blendjax")

#: RPCs a channel must carry before it pays the upgrade negotiation
#: (the 2nd RPC upgrades: one-shot probe clients never negotiate).
UPGRADE_AFTER = 2

#: per-control-RPC reply deadline during the upgrade handshake.
UPGRADE_TIMEOUT_MS = 750


class RpcChannel:
    """One client channel: a lazy DEALER socket plus, when the peer
    cooperates, an shm ring pair it transparently prefers.

    Params
    ------
    address: str
        The peer's ZMQ endpoint (the control plane and fallback).
    shm: "auto" | bool
        ``"auto"`` upgrades when :func:`blendjax.btt.shm_rpc.enabled`
        and the peer accepts; ``False`` pins to ZMQ; ``True`` insists
        on attempting even off-Linux (it will fail closed to ZMQ).
    upgrade_after: int
        RPC count before the first upgrade attempt.
    shm_chaos: ShmChaos | None
        Frame-layer fault injection attached to the upgraded channel
        (tests only).
    """

    def __init__(self, address, *, context=None, shm="auto",
                 upgrade_after=UPGRADE_AFTER, req_capacity=None,
                 shared_bell=None, shm_chaos=None, view_replies=False,
                 name="rpc"):
        self.address = address
        self.name = name
        self._ctx = context
        self._zsock = None
        self._shm = None
        self._shm_allowed = (
            shm_rpc.enabled() if shm == "auto" else bool(shm)
        )
        self._upgrade_after = int(upgrade_after)
        self._req_capacity = req_capacity or shm_rpc.REQ_CAPACITY
        self._shared_bell = shared_bell
        self._chaos = shm_chaos
        #: zero-copy reply views (see ShmClientChannel.view_replies):
        #: ONLY for callers that consume a reply's arrays before their
        #: next RPC on this channel — the replay gather hot path
        self._view_replies = bool(view_replies)
        self._state = "idle"  # idle | active | backoff | off
        self._rpcs = 0
        self._alive = False
        self._backoff_s = 1.0
        self._next_try = 0.0
        self._last_via = "tcp"
        #: transport generation: bumps on every successful upgrade —
        #: the observable ring-generation counter (tests, stats)
        self.generations = 0

    # -- introspection -------------------------------------------------------

    @property
    def transport(self):
        """The wire the NEXT request will ride: ``"shm"`` or ``"tcp"``."""
        return "shm" if self._shm is not None else "tcp"

    @property
    def shm_active(self):
        return self._shm is not None

    # -- plumbing ------------------------------------------------------------

    def _sock(self):
        import zmq

        if self._zsock is None:
            ctx = self._ctx or zmq.Context.instance()
            s = ctx.socket(zmq.DEALER)
            s.setsockopt(zmq.LINGER, 0)
            s.connect(self.address)
            self._zsock = s
        return self._zsock

    def _demote(self, reason):
        if self._shm is None:
            return
        chan, self._shm = self._shm, None
        try:
            chan.close(unlink=True)
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass
        self._state = "backoff"
        self._alive = False  # re-upgrade only after a ZMQ reply proves
        self._next_try = time.monotonic() + self._backoff_s
        self._backoff_s = min(self._backoff_s * 2, 30.0)
        logger.warning(
            "%s (%s): shm channel demoted to zmq (%s)",
            self.name, self.address, reason,
        )

    # -- upgrade -------------------------------------------------------------

    def _should_upgrade(self):
        return (
            self._shm_allowed
            and self._state != "off"
            and self._rpcs >= self._upgrade_after
            and self._alive
            and time.monotonic() >= self._next_try
        )

    def _rpc_inline(self, payload, timeout_ms):
        """One private control RPC over the ZMQ socket (own correlation
        id; stale replies of earlier workload attempts are dropped)."""
        import zmq

        msg = dict(payload)
        mid = wire.stamp_message_id(msg)
        sock = self._sock()
        wire.send_message_dealer(sock, msg)
        deadline = time.monotonic() + timeout_ms / 1000.0
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            if sock.poll(max(1, int(remaining * 1000)), zmq.POLLIN):
                reply = wire.recv_message_dealer(sock)
                if reply.get(wire.BTMID_KEY) == mid:
                    wire.pop_spans(reply)
                    return reply

    def _try_upgrade(self):
        self._next_try = time.monotonic() + self._backoff_s
        r1 = self._rpc_inline(
            {"cmd": "shm_connect", "host": shm_rpc.host_token()},
            UPGRADE_TIMEOUT_MS,
        )
        if r1 is None:
            self._backoff_s = min(self._backoff_s * 2, 30.0)
            return
        if "error" in r1 or "shm_channel" not in r1:
            # a considered refusal (kill-switch, host mismatch, a
            # pre-ShmRPC server): permanent for this channel
            self._state = "off"
            logger.info(
                "%s (%s): shm upgrade refused (%s)", self.name,
                self.address, r1.get("error", "no channel"),
            )
            return
        chan = None
        try:
            chan = shm_rpc.ShmClientChannel(
                r1["shm_channel"], r1["shm_bell"],
                req_capacity=self._req_capacity,
                bell=self._shared_bell, chaos=self._chaos,
                view_replies=self._view_replies,
            )
            r2 = self._rpc_inline(
                {"cmd": "shm_attach", "channel": chan.name,
                 "bell": chan.bell_path},
                UPGRADE_TIMEOUT_MS,
            )
            if r2 is None or "error" in r2:
                raise ConnectionError(
                    (r2 or {}).get("error", "shm_attach timed out")
                )
            chan.finish(open_timeout_ms=2000)
        except Exception as exc:  # noqa: BLE001 - degrade, never fail
            if chan is not None:
                try:
                    chan.close(unlink=True)
                except Exception:  # noqa: BLE001
                    pass
            self._state = "backoff"
            self._backoff_s = min(self._backoff_s * 2, 30.0)
            logger.info(
                "%s (%s): shm upgrade failed, staying on zmq (%s: %s)",
                self.name, self.address, type(exc).__name__, exc,
            )
            return
        self._shm = chan
        self._state = "active"
        self._backoff_s = 1.0
        self.generations += 1
        logger.info(
            "%s (%s): upgraded to shm channel %s (generation %d)",
            self.name, self.address, chan.name, self.generations,
        )

    # -- the channel protocol ------------------------------------------------

    def send_request(self, msg, raw_buffers=False):
        self._rpcs += 1
        if self._shm is None and self._should_upgrade():
            self._try_upgrade()
        if self._shm is not None:
            try:
                frames = wire.encode(msg, raw_buffers=raw_buffers)
                if self._shm.send(frames, timeout_ms=1000):
                    self._last_via = "shm"
                    return
                self._demote("request ring full")
            except ValueError:
                # request larger than the ring: this one rides ZMQ,
                # the channel itself stays upgraded
                pass
            except (OSError, EOFError) as exc:
                self._demote(f"{type(exc).__name__}: {exc}")
        wire.send_message_dealer(self._sock(), msg,
                                 raw_buffers=raw_buffers)
        self._last_via = "tcp"

    def poll_reply(self, timeout_ms):
        import zmq

        if self._last_via == "shm" and self._shm is not None:
            try:
                return self._shm.poll(timeout_ms)
            except (OSError, EOFError) as exc:
                self._demote(f"{type(exc).__name__}: {exc}")
                return False
        return bool(self._sock().poll(timeout_ms, zmq.POLLIN))

    def recv_reply(self):
        """One decoded reply, or None when the wakeup was spurious (a
        ring wrap marker, a chaos-dropped record, an oversized-reply
        stand-in)."""
        if self._last_via == "shm" and self._shm is not None:
            try:
                reply = self._shm.try_recv()
            except (OSError, EOFError) as exc:
                self._demote(f"{type(exc).__name__}: {exc}")
                return None
            if isinstance(reply, dict) and reply.get(shm_rpc.OVERFLOW_KEY):
                # the server's REAL reply did not fit the reply ring:
                # demote so the same-mid retry rides ZMQ, where any
                # size fits (mutating replies are small and cached, so
                # only idempotent reads ever re-execute here)
                self._demote("reply exceeded the reply ring capacity")
                return None
            if reply is not None:
                self._alive = True
            return reply
        reply = wire.recv_message_dealer(self._zsock)
        self._alive = True
        return reply

    def notify_timeout(self):
        """The caller's attempt deadline expired with no reply.  Over
        shm that is the death signal (a live same-host peer answers in
        microseconds; ZMQ owns slow-network waiting) — demote so the
        same-mid retry rides ZMQ to wherever the peer respawned."""
        if self._last_via == "shm":
            self._demote("reply timeout")

    def reset(self):
        """Drop BOTH transports so the next RPC dials fresh — stale
        replies of a dead server incarnation die with the old channel.
        The respawn-heal entry point: re-upgrade is re-armed (no
        backoff penalty) but still waits for a live ZMQ reply.  A
        deliberate reset/close is not a fault, so no demotion warning
        is logged."""
        if self._zsock is not None:
            self._zsock.close(0)
            self._zsock = None
        if self._shm is not None:
            chan, self._shm = self._shm, None
            try:
                chan.close(unlink=True)
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        if self._state != "off":
            self._state = "idle"
        self._next_try = 0.0
        self._backoff_s = 1.0
        self._alive = False

    def redirect(self, address):
        """Re-point the channel at a NEW peer (the sharded gateway's
        worker handoff: the front's reset reply names the worker that
        owns the lease, and steady-state traffic dials it directly).
        Both transports drop via :meth:`reset`; unlike a plain reset, a
        permanent shm refusal is also cleared — it belonged to the OLD
        peer (a pure-ZMQ front refuses, the worker it hands off to
        accepts)."""
        if address == self.address:
            return
        self.reset()
        if self._state == "off":
            self._state = "idle"
        self._rpcs = 0
        self.address = address

    def close(self):
        self.reset()

    # legacy aliases (ShardClient/ServeClient surface)
    reset_channel = reset
