"""ShmRPC: duplex shared-memory transport for same-host RPC.

``replay_shard_x`` ≈ 0.25 said the storage tier paid ~4x for loopback
ZMQ + pickle framing, and every serve-tier tick paid the same toll per
request (ROADMAP #3).  The feed path already proved the cure host-side:
the ``shm://`` ring (:mod:`blendjax.native.ring`) moves frames through
a shared-memory arena — but it is one-directional.  This module makes
it **duplex**: one RPC channel is a PAIR of SPSC rings

- ``<channel>.c2s`` — request ring, created/written by the client,
- ``<channel>.s2c`` — reply ring, created/written by the server,

plus two fd-shaped doorbells (:class:`blendjax.native.ring.DoorBell`
FIFOs: the server's bell is shared by all its channels and registered
in its ``zmq.Poller`` next to the ZMQ socket; each channel's client
bell wakes the blocking RPC wait) so neither side sleep-polls.

Frames inside a ring record are the EXACT :func:`blendjax.wire.encode`
multipart encoding — ``BTMID_KEY`` correlation ids, span piggybacks,
raw-buffer array frames, and the exactly-once reply-cache discipline in
:func:`blendjax.btt.rpc.exactly_once_rpc` ride through unchanged; only
the bytes' route differs (one GIL-released memcpy into the arena and
one out, instead of pickle + two kernel copies per direction).

Rendezvous rides the ZMQ channel (which stays the **control plane** and
the remote-peer fallback): a client that wants the upgrade sends two
uncounted control RPCs over its DEALER socket —

1. ``shm_connect {host}`` — the server verifies the host token (same
   machine, same boot) and allocates a channel name under its base;
2. (client creates its ring + bell) ``shm_attach {channel, bell}`` —
   the server opens the request ring, creates the reply ring, and from
   then on serves the channel from its main loop.

Naming: every object of one server lives under its ``base`` prefix
(``/dev/shm/{base}*``) — the server's bell, every channel's rings and
client bells.  Supervised fleets pass ``--shm-base`` so the PARENT
knows the prefix: teardown and the watchdog respawn path sweep
``unlink_base(base)``, which is what keeps SIGKILLed servers from
leaking ``/dev/shm`` objects across chaos runs.

Respawn heal: a SIGKILLed server's channels go silent (its reply ring
object lingers but nothing writes it).  The client's attempt times out,
the channel **demotes to ZMQ** (whose reconnect reaches the respawned
process), the fault-policy retry rides the SAME correlation id exactly
as it does over TCP today, and once a ZMQ reply proves the server alive
the client re-upgrades onto a fresh ring generation — the
generation-remap pattern of ``ShmRingReader.auto_reopen``, driven from
the RPC layer.  ``BJX_NO_SHM_RPC=1`` kills the whole transport (both
sides), leaving the ZMQ path byte-identical to the pre-ShmRPC code.

See docs/transport.md.
"""

from __future__ import annotations

import logging
import os
import select
import socket as _socket
import sys
import time

from blendjax import wire

logger = logging.getLogger("blendjax")

#: kill-switch: set to 1 to disable shm RPC everywhere (servers bind no
#: shm endpoint, clients never attempt the upgrade) — the ZMQ fallback
#: path is then byte-identical in behavior to the pre-ShmRPC code.
KILL_ENV = "BJX_NO_SHM_RPC"

#: control commands (answered at the transport layer, never counted in
#: the serve/replay request vocabularies and never forwarded by the
#: gateway — they negotiate the wire, they are not workload)
CONTROL_CMDS = ("shm_connect", "shm_attach")

#: default ring capacities.  /dev/shm is tmpfs: pages allocate on first
#: touch, so a generous reply ring costs address space, not memory,
#: until real traffic fills it.  A message larger than its ring cannot
#: be sent at all (the ring holds whole records) — the client falls
#: back to ZMQ for oversized requests, and a server reply that cannot
#: fit is answered with an actionable error naming the knob.
REQ_CAPACITY = 16 << 20
REP_CAPACITY = 32 << 20

#: how long a server blocks writing a reply into a full reply ring
#: before dropping it (a client that stopped reading is crashed or
#: demoted; its retry re-fetches through the reply cache over ZMQ).
SEND_TIMEOUT_MS = 200

#: key stamped into the stand-in reply a server sends when the REAL
#: reply exceeded the reply ring: an :class:`~blendjax.btt.transport.
#: RpcChannel` that sees it demotes to ZMQ and treats the reply as
#: never-delivered, so the same-mid retry rides ZMQ — where any size
#: fits (mutating commands never hit this: their replies are small and
#: the retry is answered from the reply cache either way).  Clients
#: without the channel layer surface the embedded error text instead.
OVERFLOW_KEY = "bjx_shm_overflow"


def enabled():
    """True when this process may speak shm RPC at all: Linux with a
    ``/dev/shm``, the native ring built, and no kill-switch."""
    if os.environ.get(KILL_ENV, "") not in ("", "0"):
        return False
    if not sys.platform.startswith("linux") or not os.path.isdir("/dev/shm"):
        return False
    from blendjax.native import ring

    return ring.native_available()


def host_token():
    """Identity of this machine's ``/dev/shm`` namespace: hostname +
    boot id.  Two processes that disagree cannot share memory, so the
    server refuses their ``shm_connect`` before paying any ring-open
    timeout (a containerized peer on the same kernel but a private
    ``/dev/shm`` still fails the attach open and degrades to ZMQ)."""
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            boot = f.read().strip()
    except OSError:
        boot = ""
    return f"{_socket.gethostname()}|{boot}"


def new_base(tag="srv"):
    """A fresh server base prefix.  Supervised fleets generate one per
    server UP FRONT and pass it via ``--shm-base``, so the parent can
    :func:`unlink_base` everything the (possibly SIGKILLed) server and
    its clients created."""
    return f"bjxrpc-{tag}-{os.getpid():x}-{wire.new_message_id()[:8]}"


def unlink_base(base):
    """Remove every ``/dev/shm`` object under ``base`` (rings, bells —
    the server's AND its clients', which name their objects under the
    server-allocated channel prefix).  Returns the paths removed."""
    import glob

    removed = []
    for path in glob.glob(f"/dev/shm/{base}*"):
        try:
            os.unlink(path)
            removed.append(path)
        except OSError:
            pass
    return removed


def leaked_objects(base):
    """``/dev/shm`` paths still present under ``base`` (the chaos-test
    leak check)."""
    import glob

    return sorted(glob.glob(f"/dev/shm/{base}*"))


#: the transport-neutral wire-bytes unit (one definition, wire.py's)
frames_nbytes = wire.frames_nbytes


def control_reply(transport, msg):
    """Answer a shm control command, or return None for workload
    traffic.  Every server recv path calls this FIRST: control commands
    never reach the request counters, the reply cache, or (gateway) the
    fleet.  ``transport=None`` (shm disabled/unsupported) answers with
    the actionable refusal the client's upgrade logic treats as
    permanent."""
    cmd = msg.get("cmd")
    if cmd not in CONTROL_CMDS:
        return None
    if transport is None:
        reply = {"error": "shm rpc disabled on this server"}
    else:
        try:
            reply = transport.handle_control(msg)
        except Exception as exc:  # noqa: BLE001 - surfaced to the client
            logger.exception("shm rpc: %r failed", cmd)
            reply = {"error": f"{type(exc).__name__}: {exc}"}
    mid = msg.get(wire.BTMID_KEY)
    if mid is not None:
        reply[wire.BTMID_KEY] = mid
    return reply


class ServerChannel:
    """One accepted client channel, server side: the request-ring
    reader, the reply-ring writer, and the client's bell."""

    #: duck-type marker: server reply paths dispatch idents on it
    shm_channel = True

    __slots__ = ("name", "reader", "writer", "bell", "t_accept")

    def __init__(self, name, reader, writer, bell):
        self.name = name
        self.reader = reader
        self.writer = writer
        self.bell = bell
        self.t_accept = time.monotonic()


class ShmRpcServer:
    """The server half of the transport: accepts channels negotiated
    over the ZMQ control plane and pumps them from the server's main
    loop.

    Params
    ------
    base: str | None
        ``/dev/shm`` name prefix for every object of this server
        (``--shm-base`` from a supervising parent; generated when None).
    req_capacity / rep_capacity: int
        Ring sizes for channels this server accepts.
    counters / bytes_counter: EventCounters | None, str | None
        When given, every request/reply payload byte moved through shm
        lands on ``bytes_counter`` (e.g. ``replay_shm_bytes``) — the
        observable half of the shm-vs-tcp byte saving.
    """

    def __init__(self, base=None, *, req_capacity=REQ_CAPACITY,
                 rep_capacity=REP_CAPACITY, counters=None,
                 bytes_counter=None, who="server"):
        from blendjax.native.ring import DoorBell

        self.base = base or new_base()
        self.who = who
        self.req_capacity = int(req_capacity)
        self.rep_capacity = int(rep_capacity)
        self.counters = counters
        self.bytes_counter = bytes_counter
        self._chan_seq = 0
        self._channels = {}  # name -> ServerChannel
        self._pending = {}   # name -> allocation awaiting shm_attach
        #: channels with committed-but-unannounced records (batched
        #: doorbell: a burst of co-admitted replies costs ONE wake per
        #: channel, flushed by :meth:`flush_bells` at the end of the
        #: burst instead of one ding per record)
        self._deferred_bells = set()
        self.bell = DoorBell(f"/dev/shm/{self.base}.bell", create=True)

    # -- advertisement -------------------------------------------------------

    @property
    def endpoint(self):
        """The advertised ``shm://`` endpoint (launch-info / hello
        surface).  It names the server's object prefix — rendezvous
        itself still rides the ZMQ control plane."""
        return f"shm://{self.base}"

    def info(self):
        """Capability blob for ``hello``/``telemetry`` replies."""
        return {
            "endpoint": self.endpoint,
            "host": host_token(),
            "channels": len(self._channels),
        }

    @property
    def fd(self):
        """The bell fd to register in the serve loop's poller."""
        return self.bell.fd

    # -- control plane -------------------------------------------------------

    def handle_control(self, msg):
        cmd = msg.get("cmd")
        if cmd == "shm_connect":
            peer = msg.get("host")
            if peer != host_token():
                return {"error": (
                    "shm rpc needs a same-host peer (host token "
                    f"mismatch: {peer!r} vs {host_token()!r}); use tcp"
                )}
            self._chan_seq += 1
            name = f"{self.base}.c{self._chan_seq:x}"
            self._pending[name] = time.monotonic()
            # forget stale allocations whose client never attached
            cutoff = time.monotonic() - 30.0
            for stale in [n for n, t in self._pending.items() if t < cutoff]:
                del self._pending[stale]
            return {
                "shm_channel": name,
                "shm_bell": self.bell.path,
                "shm_req_capacity": self.req_capacity,
                "shm_rep_capacity": self.rep_capacity,
            }
        if cmd == "shm_attach":
            from blendjax.native.ring import (
                DoorBell,
                ShmRingReader,
                ShmRingWriter,
            )

            name = msg.get("channel")
            if name not in self._pending:
                return {"error": (
                    f"unknown shm channel {name!r} (never allocated, "
                    "expired, or a previous server incarnation's): "
                    "reconnect"
                )}
            del self._pending[name]
            # the client created its ring before sending shm_attach, so
            # this open is immediate; a short timeout still bounds a
            # liar/racing peer
            reader = ShmRingReader(f"shm://{name}.c2s",
                                   open_timeout_ms=2000, auto_reopen=False)
            writer = ShmRingWriter(f"shm://{name}.s2c",
                                   capacity_bytes=self.rep_capacity)
            bell_path = msg.get("bell")
            bell = DoorBell(bell_path) if bell_path else None
            self._channels[name] = ServerChannel(name, reader, writer, bell)
            logger.info("%s: shm channel %s attached", self.who, name)
            return {"shm_ok": True, "channel": name}
        raise ValueError(f"unknown shm control command {cmd!r}")

    # -- data plane ----------------------------------------------------------

    def pump(self, handler):
        """Drain the bell and every channel's request ring; each decoded
        request dict goes to ``handler(channel, msg)``.  Returns the
        number of requests dispatched.  A vanished/closed request ring
        drops its channel (the client demoted, died, or reconnected
        under a new name); an undecodable record costs that record only
        — the same survival discipline as ``drain_socket``."""
        self.bell.drain()
        n = 0
        for chan in list(self._channels.values()):
            while True:
                try:
                    frames = chan.reader.recv_frames(0)
                except (EOFError, ConnectionResetError):
                    self._drop(chan)
                    break
                if frames is None:
                    break
                if self.counters is not None and self.bytes_counter:
                    self.counters.incr(self.bytes_counter,
                                       frames_nbytes(frames))
                try:
                    msg = wire.decode(frames)
                except Exception as exc:  # noqa: BLE001 - tier survives
                    logger.warning(
                        "%s: undecodable shm request dropped (%s: %s)",
                        self.who, type(exc).__name__, exc,
                    )
                    continue
                n += 1
                try:
                    handler(chan, msg)
                except Exception:  # noqa: BLE001 - the tier survives
                    logger.exception(
                        "%s: handling an shm request failed (dropped)",
                        self.who,
                    )
        return n

    def send(self, chan, reply, raw_buffers=True, ding=True):
        """Write one reply to a channel and ding its bell.  False when
        the reply could not be delivered (full ring / dead channel) —
        the client's same-mid retry re-fetches it from the reply cache,
        over whichever transport it lands on.  ``ding=False`` defers
        the wake to the caller's next :meth:`flush_bells` — the batched
        multi-record doorbell a reply burst rides (the record is
        committed and readable either way; only the wake is deferred,
        so the flush MUST come before the sender blocks)."""
        try:
            frames = wire.encode(reply, raw_buffers=raw_buffers)
            ok = chan.writer.send_frames(frames, timeout_ms=SEND_TIMEOUT_MS)
        except ValueError:
            # reply larger than the reply ring: answer with an
            # OVERFLOW_KEY stand-in — the client channel demotes and
            # its same-mid retry rides ZMQ, where any size fits (the
            # embedded text serves channel-less consumers)
            err = {
                OVERFLOW_KEY: True,
                "error": (
                    "reply exceeds the shm reply ring capacity "
                    f"({self.rep_capacity} bytes); served over zmq "
                    "instead (raise rep_capacity= to keep such replies "
                    "on shm)"
                ),
            }
            mid = reply.get(wire.BTMID_KEY)
            if mid is not None:
                err[wire.BTMID_KEY] = mid
            frames = wire.encode(err)
            try:
                ok = chan.writer.send_frames(frames,
                                             timeout_ms=SEND_TIMEOUT_MS)
            except OSError:
                return False
        except OSError:
            return False
        if ok:
            if self.counters is not None and self.bytes_counter:
                self.counters.incr(self.bytes_counter,
                                   frames_nbytes(frames))
            self._ding(chan, ding)
        return ok

    def _ding(self, chan, now):
        if chan.bell is None:
            return
        if now:
            chan.bell.ding()
        else:
            self._deferred_bells.add(chan.name)

    def flush_bells(self):
        """Ring every bell deferred by ``send(..., ding=False)`` /
        ``commit_send(..., ding=False)`` — one ding per channel however
        many records the burst committed.  Dropped channels are skipped
        (their client is gone; its retry re-dials)."""
        if not self._deferred_bells:
            return 0
        n = 0
        for name in self._deferred_bells:
            chan = self._channels.get(name)
            if chan is not None and chan.bell is not None:
                chan.bell.ding()
                n += 1
        self._deferred_bells.clear()
        return n

    def begin_send(self, chan, sizes):
        """Zero-copy reply: reserve one ring record shaped as a
        ``len(sizes)``-frame wire message and return one writable
        ``uint8`` view per frame — the server assembles the reply
        DIRECTLY in shared memory (e.g. a columnar gather lands its
        batch in the ring, skipping the staging copy the dict-encode
        path pays).  Publish with :meth:`commit_send`.  Returns None
        when unavailable (ring full, reply too big, old native layer)
        — callers fall back to :meth:`send`."""
        import struct

        n = len(sizes)
        head = 4 + 8 * n
        total = head + sum(sizes)
        try:
            view = chan.writer.begin_record(total,
                                            timeout_ms=SEND_TIMEOUT_MS)
        except (ValueError, OSError):
            # too big for the ring, or the channel was dropped between
            # recv and reply: the generic send path owns the outcome
            return None
        if view is None:
            return None
        struct.pack_into("<I", view, 0, n)
        struct.pack_into(f"<{n}Q", view, 4, *sizes)
        out, off = [], head
        for ln in sizes:
            out.append(view[off:off + ln])
            off += ln
        if self.counters is not None and self.bytes_counter:
            self.counters.incr(self.bytes_counter, sum(sizes))
        return out

    def commit_send(self, chan, ding=True):
        """Publish the record reserved by :meth:`begin_send` and wake
        the client (``ding=False`` defers the wake to
        :meth:`flush_bells`, same contract as ``send``)."""
        chan.writer.commit_record()
        self._ding(chan, ding)

    def _drop(self, chan):
        self._channels.pop(chan.name, None)
        self._deferred_bells.discard(chan.name)
        try:
            chan.reader.close(unlink=True)
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass
        try:
            chan.writer.close(unlink=True)
        except Exception:  # noqa: BLE001
            pass
        if chan.bell is not None:
            chan.bell.close(unlink=False)
        # the client's bell fifo rides the channel prefix; sweep it so
        # a churning client population cannot accumulate stale fifos
        try:
            os.unlink(f"/dev/shm/{chan.name}.cbell")
        except OSError:
            pass
        logger.info("%s: shm channel %s dropped", self.who, chan.name)

    def close(self, unlink=True):
        for chan in list(self._channels.values()):
            self._drop(chan)
        self.bell.close(unlink=unlink)
        if unlink:
            unlink_base(self.base)


class ShmClientChannel:
    """The client half of one duplex channel: request-ring writer,
    reply-ring reader, and the two bells.  Built in two steps around
    the ``shm_attach`` control RPC (create -> attach -> :meth:`finish`).

    ``chaos`` accepts a :class:`ShmChaos` shim for deterministic
    frame-layer fault injection (the ChaosProxy analogue for a wire
    with no TCP segment to drop).

    ``view_replies=True`` turns on the zero-copy reply path: array
    leaves of a received reply are views INTO the ring record, which
    stays held until the channel's next operation (send/poll/recv/
    close) releases it.  Callers on this mode must consume a reply's
    arrays (copy/scatter them into their destination) before issuing
    the next RPC — the replay gather hot path does exactly that, and
    saves one full reply copy plus a fresh multi-MB allocation per
    RPC.  ``BJX_SHM_POISON=1`` arms the use-after-release guard
    underneath (see :class:`blendjax.native.ring.ShmRingReader`)."""

    def __init__(self, name, server_bell_path, *, req_capacity=REQ_CAPACITY,
                 bell=None, chaos=None, view_replies=False):
        from blendjax.native.ring import DoorBell, ShmRingWriter

        self.name = name
        self.writer = ShmRingWriter(f"shm://{name}.c2s",
                                    capacity_bytes=req_capacity)
        #: reply-wake bell: owned per-channel by default; a caller that
        #: multiplexes many channels in one loop (the gateway's replica
        #: backends) passes its shared bell instead
        self._own_bell = bell is None
        self.bell = bell if bell is not None else DoorBell(
            f"/dev/shm/{name}.cbell", create=True
        )
        self.server_bell = DoorBell(server_bell_path)
        self.reader = None  # until finish()
        self.chaos = chaos
        self.view_replies = bool(view_replies)
        self._held = False  # a viewed record awaiting release
        #: payload bytes moved through this channel (both directions)
        self.bytes_moved = 0

    @property
    def bell_path(self):
        return self.bell.path

    def finish(self, open_timeout_ms=2000):
        """Open the reply ring (the server created it while handling
        ``shm_attach``, so this is immediate)."""
        from blendjax.native.ring import ShmRingReader

        self.reader = ShmRingReader(f"shm://{self.name}.s2c",
                                    open_timeout_ms=open_timeout_ms,
                                    auto_reopen=False)
        return self

    # -- data plane ----------------------------------------------------------

    def release(self):
        """Release the ring record whose views the last ``view_replies``
        reply handed out (no-op otherwise).  Called automatically at
        the next channel operation."""
        if self._held:
            self._held = False
            self.reader.release_record()

    def send(self, frames, timeout_ms=1000):
        """Write one request; True when delivered.  Raises ValueError
        for a request larger than the ring (the caller falls back to
        ZMQ for that message) and OSError family when the channel is
        dead."""
        self.release()
        sends = (self.chaos.on_send(frames) if self.chaos is not None
                 else (frames,))
        for f in sends:
            if not self.writer.send_frames(f, timeout_ms=timeout_ms):
                return False
            self.bytes_moved += frames_nbytes(f)
            self.server_bell.ding()
        # a chaos-dropped request (empty ``sends``) reports True: the
        # loss is silent by design — the caller's reply timeout and
        # same-mid retry are what the fault exercises
        return True

    def try_recv(self):
        """One reply dict if a record is pending, else None.  Raises
        ``ConnectionResetError``/``EOFError`` when the server side is
        gone (vanished ring / clean close) — the demote signal.  On
        ``view_replies`` channels the reply's array leaves view the
        ring record (held until the next channel operation)."""
        if self.reader is None:
            return None
        self.release()
        while True:
            if self.view_replies:
                frames = self.reader.recv_frames_view(0)
            else:
                frames = self.reader.recv_frames(0)
            if frames is None:
                if self.chaos is not None:
                    dup = self.chaos.take_pending_dup()
                    if dup is not None:
                        return wire.decode(dup)
                return None
            if self.view_replies:
                self._held = True
            self.bytes_moved += frames_nbytes(frames)
            if self.chaos is not None:
                frames = self.chaos.on_recv(frames)
                if frames is None:
                    self.release()
                    continue  # dropped reply: keep draining
            try:
                return wire.decode(frames)
            except Exception as exc:  # noqa: BLE001 - record-scoped
                logger.warning(
                    "shm channel %s: undecodable reply dropped (%s: %s)",
                    self.name, type(exc).__name__, exc,
                )
                self.release()
                continue

    def poll(self, timeout_ms):
        """True when a reply record is (probably) pending — parks on
        the bell fd, so the wait is event-driven, and falls back to the
        ring's own bounded wait when the bell has no fd.  Releases any
        record the PREVIOUS viewed reply held (by the time the caller
        polls again, it has processed that reply)."""
        self.release()
        deadline = time.monotonic() + timeout_ms / 1000.0
        while True:
            if self.reader is not None and self.reader.pending_bytes() > 0:
                return True
            if self.chaos is not None and self.chaos.has_pending_dup():
                return True
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            fd = self.bell.fd
            if fd is None:
                return False
            r, _, _ = select.select([fd], [], [], min(remaining, 0.05))
            if r:
                self.bell.drain()

    def close(self, unlink=True):
        if self.reader is not None:
            try:
                self.reader.close(unlink=unlink)
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
            self.reader = None
        try:
            self.writer.close(unlink=unlink)
        except Exception:  # noqa: BLE001
            pass
        if self._own_bell:
            self.bell.close(unlink=unlink)
        self.server_bell.close(unlink=False)


class ShmChaos:
    """Deterministic frame-layer fault injection for the shm wire — the
    :class:`~blendjax.btt.chaos.ChaosProxy` analogue for a transport
    with no TCP chunk to intercept.  Attached to a
    :class:`ShmClientChannel` (``chan.chaos = ShmChaos()``); actions
    are consumed one per frame-list in schedule order.

    - ``drop_next("up")``    — the next request is never written (lost
      datagram: the client's reply timeout and same-mid retry heal it).
    - ``dup_next("up")``     — the next request is written twice: the
      server's reply cache / in-queue dedupe must make it exactly-once.
    - ``garble_next("up")``  — deterministic byte flips in the next
      request's header frame: the server must drop the record and
      survive.
    - ``drop_next("down")``  — the next reply is read and discarded
      (lost reply: the client's same-mid retry must be answered from
      the reply cache without re-execution).
    - ``dup_next("down")``   — the next reply is delivered twice: the
      second must be dropped as stale by the mid discipline.
    """

    def __init__(self, seed=0):
        import random

        self._rng = random.Random(seed)
        self._sched = {"up": [], "down": []}
        self._dup_down = None
        self.dropped = 0
        self.duplicated = 0
        self.garbled = 0

    def _push(self, direction, action):
        self._sched[direction].append(action)

    def drop_next(self, direction="down"):
        self._push(direction, "drop")

    def dup_next(self, direction="down"):
        self._push(direction, "dup")

    def garble_next(self, direction="up"):
        self._push(direction, "garble")

    # -- channel hooks -------------------------------------------------------

    def on_send(self, frames):
        """Request-path hook: returns the tuple of frame-lists to
        actually write."""
        if not self._sched["up"]:
            return (frames,)
        action = self._sched["up"].pop(0)
        if action == "drop":
            self.dropped += 1
            return ()
        if action == "dup":
            self.duplicated += 1
            return (frames, frames)
        if action == "garble":
            head = bytearray(
                frames[0].tobytes() if hasattr(frames[0], "tobytes")
                else bytes(frames[0])
            )
            for _ in range(max(1, len(head) // 64)):
                head[self._rng.randrange(len(head))] ^= 0xFF
            self.garbled += 1
            return ([bytes(head)] + list(frames[1:]),)
        return (frames,)

    def on_recv(self, frames):
        """Reply-path hook: returns frames to deliver, or None (drop)."""
        if not self._sched["down"]:
            return frames
        action = self._sched["down"].pop(0)
        if action == "drop":
            self.dropped += 1
            return None
        if action == "dup":
            self.duplicated += 1
            self._dup_down = [
                bytes(f) if not hasattr(f, "tobytes") else f.tobytes()
                for f in frames
            ]
            return frames
        return frames

    def has_pending_dup(self):
        return self._dup_down is not None

    def take_pending_dup(self):
        dup, self._dup_down = self._dup_down, None
        return dup
