"""Consumer-side duplex channel (reference ``btt/duplex.py:8-67``):
connects to the producer's bound PAIR socket."""

from __future__ import annotations

from blendjax._duplex import DuplexChannelBase
from blendjax.btt.constants import DEFAULT_TIMEOUTMS


class DuplexChannel(DuplexChannelBase):
    DEFAULT_TIMEOUTMS = DEFAULT_TIMEOUTMS

    def __init__(self, address, btid=None, lingerms=0, timeoutms=None, raw_buffers=False):
        super().__init__(
            address,
            btid=btid,
            bind=False,
            lingerms=lingerms,
            timeoutms=timeoutms,
            raw_buffers=raw_buffers,
        )
