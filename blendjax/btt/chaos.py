"""Deterministic fault injection for fleet testing.

The failure paths in :mod:`blendjax.btt.faults`, :mod:`.envpool` and
:mod:`.supervise` are only trustworthy if they can be exercised *on
demand* — not by hoping a sleep lines up with a crash.  This module
provides:

- :class:`ChaosProxy` — a wire-level TCP relay to park between a consumer
  and one producer endpoint.  It can **stall** (stop forwarding: the
  consumer sees silence, exactly like a hung renderer), **cut** (close
  live connections mid-message: a crashed peer at the TCP layer), and
  **drop / duplicate / garble / delay** individual chunks, either
  programmatically or on a deterministic per-chunk schedule.  Byte
  positions for garbling come from a seeded ``random.Random``.
- :func:`kill_instance` — SIGKILL a launched producer's whole process
  group (no cleanup runs: shm rings linger, sockets die mid-message —
  the honest crash).

Determinism notes: chunk indices count ``recv()`` chunks per direction —
with request/reply traffic (REQ/REP envs) each message is one chunk after
the ZMQ handshake, so schedules are reproducible; for firehose PUSH/PULL
streams prefer the programmatic controls (``stall``/``cut``), which do
not depend on TCP segmentation.  None of this needs elevated privileges
or external tools, so the chaos tests run in any CI container.
"""

from __future__ import annotations

import logging
import os
import random
import signal
import socket
import threading
import time

logger = logging.getLogger("blendjax")

#: Actions a schedule entry may name.
ACTIONS = ("drop", "dup", "garble", "close", "delay")


def _parse_endpoint(endpoint):
    """'tcp://host:port' | (host, port) | port -> (host, port)."""
    if isinstance(endpoint, int):
        return "127.0.0.1", endpoint
    if isinstance(endpoint, (tuple, list)):
        return endpoint[0], int(endpoint[1])
    addr = endpoint
    if addr.startswith("tcp://"):
        addr = addr[len("tcp://"):]
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


class ChaosProxy:
    """TCP relay with scheduled and programmatic fault injection.

    Point the consumer at :attr:`address` instead of the producer's
    endpoint; the proxy accepts any number of consumer connections and
    pipes each to its own upstream connection.

    Params
    ------
    upstream: str | int | (host, port)
        The real producer endpoint (``tcp://host:port`` form accepted,
        so ``launch_info.addresses['GYM'][i]`` drops straight in).
    listen_host: str
        Interface to listen on (an ephemeral port is chosen).
    seed: int
        Seeds the byte-position stream used by ``garble``.
    delay_s: float
        Constant forwarding delay applied to every chunk (both
        directions) — network latency emulation.
    """

    def __init__(self, upstream, listen_host="127.0.0.1", seed=0, delay_s=0.0):
        self._up_host, self._up_port = _parse_endpoint(upstream)
        self._rng = random.Random(seed)
        self.delay_s = delay_s
        self._stop = threading.Event()
        self._open = threading.Event()
        self._open.set()
        self._lock = threading.Lock()
        self._conns = []  # live (client, upstream) socket pairs
        self._sched = {"up": {}, "down": {}}  # chunk index -> action
        self.chunks = {"up": 0, "down": 0}
        self.forwarded_bytes = {"up": 0, "down": 0}
        self.dropped = 0
        self.garbled = 0
        self.duplicated = 0
        self.cuts = 0

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((listen_host, 0))
        self._listener.listen(16)
        self.host, self.port = self._listener.getsockname()[:2]
        self.address = f"tcp://{self.host}:{self.port}"
        self._threads = [
            threading.Thread(target=self._accept_loop, daemon=True,
                             name="bjx-chaos-accept")
        ]
        self._threads[0].start()

    # -- scheduling & control ------------------------------------------------

    def at(self, chunk, action, direction="down"):
        """Schedule ``action`` for chunk index ``chunk`` of ``direction``
        ('up' = consumer->producer, 'down' = producer->consumer).
        Deterministic: the same traffic pattern hits the same chunk."""
        if action not in ACTIONS:
            raise ValueError(f"unknown chaos action {action!r}; one of {ACTIONS}")
        with self._lock:
            self._sched[direction][int(chunk)] = action

    def _next(self, action, direction):
        with self._lock:
            self._sched[direction][self.chunks[direction]] = action

    def drop_next(self, direction="down"):
        """Discard the next chunk (lost datagram / dropped frame)."""
        self._next("drop", direction)

    def dup_next(self, direction="down"):
        """Forward the next chunk twice (duplicated delivery)."""
        self._next("dup", direction)

    def garble_next(self, direction="down"):
        """Flip deterministic bytes in the next chunk (corruption; a ZMQ
        peer treats this as a protocol violation and drops the
        connection, which is the point)."""
        self._next("garble", direction)

    def close_next(self, direction="down"):
        """Close both sides when the next chunk arrives — the
        kill-mid-message case: the peer crashed while its reply was on
        the wire."""
        self._next("close", direction)

    def stall(self):
        """Stop forwarding in both directions (hung producer): the
        consumer sees silence until :meth:`resume`, not a disconnect."""
        self._open.clear()

    def resume(self):
        self._open.set()

    def cut(self):
        """Close every live connection now (crashed peer).  The listener
        stays up, so ZMQ's automatic reconnect comes back through the
        proxy."""
        with self._lock:
            conns, self._conns = self._conns, []
        for pair in conns:
            self._close_pair(pair)
        if conns:
            self.cuts += 1

    # -- plumbing ------------------------------------------------------------

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                client, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            try:
                up = socket.create_connection(
                    (self._up_host, self._up_port), timeout=10
                )
            except OSError:
                client.close()
                time.sleep(0.05)  # upstream down: shed and let ZMQ redial
                continue
            for s in (client, up):
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            pair = (client, up)
            with self._lock:
                self._conns.append(pair)
            for src, dst, direction in (
                (client, up, "up"), (up, client, "down"),
            ):
                t = threading.Thread(
                    target=self._pump, args=(src, dst, direction, pair),
                    daemon=True, name=f"bjx-chaos-{direction}",
                )
                t.start()
                self._threads.append(t)

    def _close_pair(self, pair):
        for s in pair:
            # shutdown first: close() alone would not terminate the
            # connection while the sibling pump thread is blocked in
            # recv() on the fd (the kernel keeps the open file
            # description alive under the in-flight syscall — no FIN
            # would ever reach the peer)
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    def _pump(self, src, dst, direction, pair):
        try:
            while not self._stop.is_set():
                try:
                    data = src.recv(65536)
                except OSError:
                    return
                if not data:
                    return
                # stall gate: hold the chunk (and everything behind it)
                while not self._open.wait(0.05):
                    if self._stop.is_set():
                        return
                with self._lock:
                    idx = self.chunks[direction]
                    self.chunks[direction] = idx + 1
                    action = self._sched[direction].pop(idx, None)
                if self.delay_s > 0:
                    time.sleep(self.delay_s)
                if action == "drop":
                    self.dropped += 1
                    continue
                if action == "close":
                    self.cuts += 1
                    self._close_pair(pair)
                    return
                if action == "garble":
                    data = bytearray(data)
                    for _ in range(max(1, len(data) // 64)):
                        data[self._rng.randrange(len(data))] ^= 0xFF
                    data = bytes(data)
                    self.garbled += 1
                try:
                    dst.sendall(data)
                    if action == "dup":
                        dst.sendall(data)
                        self.duplicated += 1
                except OSError:
                    return
                with self._lock:
                    self.forwarded_bytes[direction] += len(data)
        finally:
            self._close_pair(pair)

    def close(self):
        self._stop.set()
        self._open.set()
        try:
            self._listener.close()
        except OSError:
            pass
        self.cut()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def wait_env_ready(addresses, timeout_s=30.0):
    """Block until every GYM endpoint answers a ``reset`` handshake —
    the deterministic startup barrier for fault tests: counters measured
    after it reflect injected faults only, never producer boot time.
    Each attempt uses a throwaway REQ socket (no strict-alternation
    lockup on timeout).  Raises TimeoutError naming the silent endpoint.
    """
    import zmq

    from blendjax import wire

    ctx = zmq.Context.instance()
    deadline = time.monotonic() + timeout_s
    for addr in addresses:
        while True:
            remaining_ms = int((deadline - time.monotonic()) * 1000)
            if remaining_ms <= 0:
                raise TimeoutError(
                    f"environment at {addr} not ready within {timeout_s}s"
                )
            s = ctx.socket(zmq.REQ)
            s.setsockopt(zmq.LINGER, 0)
            s.connect(addr)
            try:
                wire.send_message(s, {"cmd": "reset", "time": None})
                if s.poll(min(1000, remaining_ms), zmq.POLLIN):
                    wire.recv_message(s)
                    break
            except zmq.Again:
                pass
            finally:
                s.close(0)


def kill_instance(launcher, idx, sig=signal.SIGKILL):
    """Kill producer ``idx``'s whole process group with no cleanup — the
    honest crash (shm rings linger, REQ/REP peers die mid-conversation).
    Returns the killed process object; pair with
    :class:`~blendjax.btt.watchdog.FleetWatchdog` / ``FleetSupervisor``
    restarts to exercise the respawn-and-resync path."""
    proc = launcher.launch_info.processes[idx]
    try:
        if os.name == "posix":
            os.killpg(os.getpgid(proc.pid), sig)
        else:  # pragma: no cover - windows CI
            proc.kill()
    except (ProcessLookupError, PermissionError):
        proc.kill()
    return proc
