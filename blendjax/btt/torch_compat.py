"""Optional torch interop for users migrating from the reference.

The reference consumer is built on ``torch.utils.data``
(``btt/dataset.py:14,119,134``); blendjax is torch-free but a one-liner
bridges back: wrap any blendjax dataset for a torch ``DataLoader``.  Import
of this module requires torch; nothing else in blendjax does.

    from blendjax.btt.torch_compat import as_torch_iterable
    loader = torch.utils.data.DataLoader(as_torch_iterable(ds), batch_size=8,
                                         num_workers=4)
"""

from __future__ import annotations

import torch.utils.data as _tud


class TorchIterableAdapter(_tud.IterableDataset):
    """Presents a blendjax RemoteIterableDataset to torch DataLoaders.

    Worker sharding matches the reference: each DataLoader worker streams
    ``max_items // num_workers`` items (handled inside
    ``RemoteIterableDataset.__iter__`` via ``get_worker_info``).
    """

    def __init__(self, dataset):
        self.dataset = dataset

    def __iter__(self):
        return iter(self.dataset)


class TorchMapAdapter(_tud.Dataset):
    """Presents FileDataset/SingleFileDataset map-style replays to torch."""

    def __init__(self, dataset):
        self.dataset = dataset

    def __len__(self):
        return len(self.dataset)

    def __getitem__(self, idx):
        return self.dataset[idx]


def as_torch_iterable(dataset):
    return TorchIterableAdapter(dataset)


def as_torch_map(dataset):
    return TorchMapAdapter(dataset)
