"""Arena-pooled batch buffers: preallocated, recycled numpy batch storage
for the zero-copy assembly path (ISSUE 1 tentpole).

The consumer hot path used to pay two avoidable host copies per batch:
per-sample views were stacked by ``collate`` into a *freshly allocated*
batch array (copy + malloc per batch) that ``device_put`` then copied
again.  An :class:`ArenaPool` removes the allocation churn and caps host
memory: a fixed set of :class:`Arena` objects — one contiguous
``(batch_size, *leaf_shape)`` buffer per pytree leaf — is recycled
batch-over-batch.  ``_BatchBuilder`` (:mod:`blendjax.btt.dataset`)
scatters incoming wire frames straight into the acquired arena at their
final batch offset; the prefetcher (:mod:`blendjax.btt.prefetch`)
releases the arena back to the freelist only once the corresponding
host->device transfer has completed, so a slow trainer backpressures
into the pool instead of allocating unboundedly.

Stage timers recorded along this path (see
:class:`blendjax.utils.timing.StageTimer`): ``arena_wait`` (time blocked
acquiring a free arena — pool exhaustion = trainer backpressure),
``scatter`` (frame decode + copy into the arena), ``recycle`` (returning
the arena after the device transfer completes).
"""

from __future__ import annotations

import threading
import time


class Arena:
    """One recyclable set of batch buffers (one ndarray per pytree leaf).

    Buffers are created lazily on first sight of each leaf's
    ``(batch_size, *shape)`` / dtype and reused verbatim on later
    batches; a leaf whose schema drifts gets its buffer replaced (the
    old one is garbage collected with the batch that still views it).
    """

    __slots__ = ("buffers", "_pool")

    def __init__(self, pool=None):
        self.buffers = {}  # path -> ndarray (batch_size, *leaf_shape)
        self._pool = pool

    def get_buffer(self, path, shape, dtype):
        """The preallocated buffer for ``path``, (re)allocated on schema
        change.  ``shape`` includes the leading batch axis."""
        import numpy as np

        buf = self.buffers.get(path)
        if buf is None or buf.shape != tuple(shape) or buf.dtype != dtype:
            buf = np.empty(shape, dtype)
            self.buffers[path] = buf
        return buf

    def release(self):
        """Return this arena to its pool (no-op for pool-less arenas)."""
        if self._pool is not None:
            self._pool.release(self)


class ArenaPool:
    """Bounded freelist of :class:`Arena` objects shared by the feed
    threads.

    ``acquire`` blocks while every arena is checked out — the pool is
    the backpressure valve between the recv/scatter threads and the
    device transfer: when the trainer falls behind, assembly stalls here
    instead of allocating new batch storage without bound.  Thread-safe
    (one pool is shared across all loader workers and the prefetch
    thread).
    """

    def __init__(self, pool_size=4):
        if pool_size < 1:
            raise ValueError(f"pool_size must be >= 1, got {pool_size}")
        self.pool_size = pool_size
        self._cond = threading.Condition()
        self._free = []
        self._created = 0

    @property
    def in_use(self):
        """Arenas currently checked out (diagnostics / tests)."""
        with self._cond:
            return self._created - len(self._free)

    def acquire(self, timeout=None, stop_event=None):
        """Next free arena; blocks while the pool is exhausted.

        Returns ``None`` when ``stop_event`` is set or ``timeout``
        (seconds) expires before an arena frees up — callers treat that
        as a shutdown/timeout signal, never as an empty batch.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                if self._free:
                    return self._free.pop()
                if self._created < self.pool_size:
                    self._created += 1
                    return Arena(self)
                if stop_event is not None and stop_event.is_set():
                    return None
                wait = 0.1
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    wait = min(wait, remaining)
                self._cond.wait(wait)

    def release(self, arena):
        """Return ``arena`` to the freelist (idempotent per checkout)."""
        with self._cond:
            if arena not in self._free:
                self._free.append(arena)
                self._cond.notify()


class ArenaBatch:
    """A collated batch whose array leaves live in a pooled arena.

    ``data`` is the plain numpy pytree (exactly what the legacy collate
    path yields); :meth:`recycle` returns the backing arena to its pool
    and MUST only be called once the batch's bytes have been consumed —
    the prefetcher calls it after the device transfer completes
    (``jax.block_until_ready``).  Idempotent: double-recycle is a no-op.

    ``meta`` carries producer-side sidecar values that live OUTSIDE the
    batch pytree — e.g. the replay sampler's ``(indices, weights)``
    pair, needed for priority updates after the learner step.  Consumers
    that unwrap ``data`` (the device prefetcher) ignore it; direct
    consumers read it before recycling.
    """

    __slots__ = ("data", "arena", "meta")

    def __init__(self, data, arena, meta=None):
        self.data = data
        self.arena = arena
        self.meta = meta

    def recycle(self):
        arena, self.arena = self.arena, None
        if arena is not None:
            arena.release()
