"""Consumer-side defaults (reference ``btt/constants.py:4``)."""

#: Default socket timeout on the training host.  Generous: Blender instances
#: can take several seconds to boot and compile shaders before first frame.
DEFAULT_TIMEOUTMS = 10000
