"""Fleet failure detection.

The reference detects producer death only when the user polls
``BlenderLauncher.assert_alive`` or when the stream times out
(``launcher.py:166-171``, ``dataset.py:98-99`` — SURVEY.md §5: "No restart,
no elasticity").  ``FleetWatchdog`` watches the fleet from a background
thread and reports deaths promptly; with ``restart=True`` it respawns dead
instances with their original command line.  Streams heal transparently on
both transports: tcp because producers bind and consumers keep their
connect-mode sockets; shm because the respawned producer recreates the
ring and :class:`blendjax.native.ring.ShmRingReader` detects the identity
change and remaps the new generation (rc -4 reopen path).
"""

from __future__ import annotations

import logging
import random
import threading

from blendjax.utils.timing import fleet_counters

logger = logging.getLogger("blendjax")


class FleetWatchdog:
    """Monitors a launched fleet.

    Params
    ------
    launcher: BlenderLauncher
        A launcher inside its context (``launch_info`` populated).
    interval: float
        Poll period, seconds.
    on_death: callable | None
        ``on_death(index, exit_code)`` invoked per death (from the watchdog
        thread).
    restart: bool
        Respawn dead instances with their original command.
    on_respawn: callable | None
        ``on_respawn(index, process)`` invoked after a SUCCESSFUL respawn
        (from the watchdog thread; only fires with ``restart=True``).
        ``on_death`` reports the loss; this reports the replacement — a
        consumer that probes/re-admits (the serve gateway, a supervisor
        heal loop) re-arms immediately instead of waiting out its next
        poll.
    respawn_backoff_s / respawn_jitter_s: float
        Pause inserted before each respawn: ``respawn_backoff_s`` fixed
        plus ``uniform(0, respawn_jitter_s)`` randomized per member.
        With N members SIGKILLed in the same poll window (or a box
        stall), the jitter de-correlates their relaunches so they do
        not come back in lockstep and stampede the gateway's
        re-admission scrape.  Applied milliseconds are counted under
        ``watchdog_backoff_jitter_ms`` so postmortems show the pacing.
    counters: EventCounters | None
        Counter sink for ``watchdog_backoff_jitter_ms`` (defaults to
        the process-wide ``fleet_counters``).
    """

    def __init__(self, launcher, interval=1.0, on_death=None, restart=False,
                 on_respawn=None, respawn_backoff_s=0.0,
                 respawn_jitter_s=0.05, counters=None):
        self.launcher = launcher
        self.interval = interval
        self.on_death = on_death
        self.restart = restart
        self.on_respawn = on_respawn
        self.respawn_backoff_s = float(respawn_backoff_s)
        self.respawn_jitter_s = float(respawn_jitter_s)
        self.counters = counters if counters is not None else fleet_counters
        self.deaths = []  # (index, exit_code, restarted)
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        if self._thread is not None:
            raise RuntimeError("watchdog already started")
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval + 5)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    @property
    def alive(self):
        """Number of currently-running instances."""
        info = self.launcher.launch_info
        if info is None or info.processes is None:
            return 0
        return sum(1 for p in info.processes
                   if p is not None and p.poll() is None)

    def _run(self):
        while not self._stop.wait(self.interval):
            info = self.launcher.launch_info
            if info is None or info.processes is None:
                return
            for idx, proc in enumerate(info.processes):
                if proc is None:
                    # retired member (autoscale scale-down): its slot is
                    # kept so fleet indices stay stable, but there is
                    # nothing to watch or respawn
                    continue
                code = proc.poll()
                if code is None:
                    continue
                already = any(d[0] == idx and not d[2] for d in self.deaths)
                restarted = False
                if self.restart:
                    delay = self.respawn_backoff_s + random.uniform(
                        0.0, self.respawn_jitter_s)
                    if delay > 0:
                        self.counters.incr(
                            "watchdog_backoff_jitter_ms",
                            max(1, int(delay * 1000.0)),
                        )
                        if self._stop.wait(delay):
                            return
                    try:
                        new = self.launcher.respawn(idx)
                    except Exception:
                        # a failed respawn (transient ENOMEM, unavailable
                        # executable) must not kill the watchdog thread:
                        # the instance is still dead next poll, so the
                        # respawn retries every interval — but the death
                        # itself is still reported (once, below) so
                        # supervisors can quarantine/alert while the
                        # producer stays down.  A later successful respawn
                        # appends a second, restarted=True record (and
                        # re-fires on_death, which re-arms the consumer
                        # resync).
                        logger.exception(
                            "respawn of instance %d failed; retrying on "
                            "the next poll", idx,
                        )
                        if already:
                            continue
                    else:
                        restarted = True
                        # resolve any earlier respawn-failed record so a
                        # future death of this instance reports again
                        self.deaths = [
                            d for d in self.deaths
                            if not (d[0] == idx and not d[2])
                        ]
                        logger.warning(
                            "instance %d died (exit %s); restarted as "
                            "pid %d", idx, code, new.pid,
                        )
                elif not already:
                    logger.warning("instance %d died (exit %s)", idx, code)
                else:
                    continue
                self.deaths.append((idx, code, restarted))
                if self.on_death is not None:
                    # an exception in user callback code must not kill the
                    # watchdog thread — it is exactly the component that
                    # must survive everything else failing
                    try:
                        self.on_death(idx, code)
                    except Exception:
                        logger.exception(
                            "watchdog on_death callback failed for "
                            "instance %d (watchdog keeps running)", idx,
                        )
                if restarted and self.on_respawn is not None:
                    # after on_death: the loss is reported before the
                    # replacement (same survival contract)
                    try:
                        self.on_respawn(idx, new)
                    except Exception:
                        logger.exception(
                            "watchdog on_respawn callback failed for "
                            "instance %d (watchdog keeps running)", idx,
                        )
