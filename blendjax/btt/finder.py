"""Locate a usable Blender executable (reference ``btt/finder.py:16-71``).

Discovery order:
1. ``$BLENDJAX_BLENDER`` — explicit executable path or wrapper script.  This
   is how headless TPU-VM deployments point at an ``xvfb-run``/EGL wrapper,
   and how CI substitutes a fake Blender (SURVEY.md §4: the reference's
   biggest testability gap is that every test needs real Blender).
2. ``blender`` on PATH (optionally extended by ``additional_blender_paths``).

The candidate is validated by parsing ``blender --version`` and smoke-testing
that its embedded Python can ``import zmq`` (same probe as the reference:
``--background --python-use-system-env --python-exit-code 255``).
"""

from __future__ import annotations

import logging
import os
import re
import shutil
import subprocess
import tempfile
from pathlib import Path

logger = logging.getLogger("blendjax")

_PROBE_SCRIPT = "import zmq\n"
_VERSION_RE = re.compile(r"Blender\s+(\d+)\.(\d+)", re.IGNORECASE)

#: Discovery result cache.  Spawning Blender (or even Python) twice per
#: launch to re-validate an executable that cannot have changed is pure
#: startup latency; keyed by (override, extra paths).
_CACHE: dict = {}


def _probe(bpath: Path, env) -> bool:
    """True if Blender's embedded Python can import zmq."""
    fd, name = tempfile.mkstemp(suffix=".py", text=True)
    try:
        with os.fdopen(fd, "w") as fp:
            fp.write(_PROBE_SCRIPT)
        result = subprocess.run(
            [
                str(bpath),
                "--background",
                "--python-use-system-env",
                "--python-exit-code",
                "255",
                "--python",
                name,
            ],
            capture_output=True,
            env=env,
            timeout=120,
        )
        return result.returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        os.unlink(name)


def discover_blender(additional_blender_paths=None, use_cache=True):
    """Return ``{'path': Path, 'major': int, 'minor': int}`` or ``None``."""
    key = (os.environ.get("BLENDJAX_BLENDER"), str(additional_blender_paths))
    if use_cache and key in _CACHE:
        return _CACHE[key]
    info = _discover_uncached(additional_blender_paths)
    if info is not None:
        _CACHE[key] = info
    return info


def _discover_uncached(additional_blender_paths=None):
    env = os.environ.copy()
    if additional_blender_paths is not None:
        env["PATH"] = str(additional_blender_paths) + os.pathsep + env.get("PATH", "")

    override = env.get("BLENDJAX_BLENDER")
    if override:
        bpath = Path(override)
        if not bpath.exists():
            logger.warning("BLENDJAX_BLENDER=%s does not exist.", override)
            return None
    else:
        found = shutil.which("blender", path=env.get("PATH"))
        if found is None:
            logger.warning("Could not find Blender on PATH.")
            return None
        bpath = Path(found).resolve()

    try:
        result = subprocess.run(
            [str(bpath), "--version"], capture_output=True, env=env, timeout=60
        )
    except (OSError, subprocess.TimeoutExpired):
        logger.warning("Failed to execute %s --version", bpath)
        return None

    match = _VERSION_RE.search(result.stdout.decode(errors="replace"))
    if result.returncode != 0 or match is None:
        logger.warning("Failed to parse Blender version from %s.", bpath)
        return None

    if not _probe(bpath, env):
        logger.warning(
            "Blender at %s cannot import zmq in its embedded Python; "
            "install blendjax's producer requirements into Blender "
            "(see scripts/install_btb.py).",
            bpath,
        )
        return None

    return {"path": bpath, "major": int(match[1]), "minor": int(match[2])}


if __name__ == "__main__":
    print(discover_blender())
