"""Threaded batch loader — the torch-DataLoader role in the TPU pipeline
(replaces reference usage ``DataLoader(ds, batch_size, num_workers=...)``,
e.g. ``examples/datagen/generate.py``, ``benchmarks/benchmark.py:26``).

Why threads instead of worker processes: the stream's hot path is ZMQ
``recv`` (GIL released in C) plus numpy buffer handling, so threads overlap
IO without the serialization tax torch pays to move tensors between worker
processes.  Each worker thread runs its own PULL socket via
``RemoteIterableDataset.stream(worker_id, num_workers)`` — identical fan-in
semantics, zero inter-process copies.

Batches are assembled *inside* the worker threads (torch DataLoader
semantics: each worker emits whole batches), which parallelizes collation
across workers and puts one queue element per batch instead of per item.

Multi-host TPU slices pass ``shard=(process_index, process_count)`` so the
global stream is split hosts × workers (SURVEY.md §2.4).
"""

from __future__ import annotations

import queue
import sys
import threading

from blendjax.btt.collate import collate as default_collate
from blendjax.utils.timing import StageTimer

_SENTINEL = object()


class BatchLoader:
    """Iterates collated batches pulled by ``num_workers`` stream threads.

    Params
    ------
    dataset: RemoteIterableDataset (or anything with ``.stream(...)``)
    batch_size: int
    num_workers: int
        Stream threads; each takes ``1/num_workers`` of ``max_items``.
    collate_fn: callable
        list-of-items -> batch pytree (default numpy collate).
    shard: (int, int)
        ``(shard_id, num_shards)`` for host-level splits on TPU pods.
    drop_last: bool
        Drop the final partial batch.
    prefetch_batches: int
        Bound on buffered items, expressed in batches.
    gate: TransferGate | None
        When set, workers pause at batch boundaries while a host->device
        transfer holds the gate closed (see ``prefetch.TransferGate``) —
        keeps feed threads off the core the transfer pump needs on
        core-starved hosts.
    arena_pool: blendjax.btt.arena.ArenaPool | None
        When set (and the dataset takes the batched path), batches
        assemble into recycled arena buffers and come out as
        ``ArenaBatch`` objects; the consumer must recycle each one after
        its bytes are consumed (the device prefetcher does this once the
        transfer completes).  Pool exhaustion backpressures the workers.
    """

    def __init__(
        self,
        dataset,
        batch_size,
        num_workers=1,
        collate_fn=None,
        shard=(0, 1),
        drop_last=True,
        prefetch_batches=2,
        timer=None,
        gate=None,
        arena_pool=None,
    ):
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        self.dataset = dataset
        self.batch_size = batch_size
        self.num_workers = num_workers
        self.collate_fn = collate_fn or default_collate
        self.shard = shard
        self.drop_last = drop_last
        self.gate = gate
        self.arena_pool = arena_pool
        self.timer = timer or StageTimer()
        self._queue = queue.Queue(maxsize=max(2, prefetch_batches))
        self._stop = threading.Event()
        self._threads = []
        self._started = False

        # Batching happens per worker: a worker that never accumulates a full
        # batch yields nothing under drop_last, which silently drops the whole
        # stream when batch_size exceeds the per-worker item count.
        max_items = getattr(dataset, "max_items", None)
        if drop_last and max_items is not None:
            per_worker = max_items // (num_workers * shard[1])
            if per_worker < batch_size:
                raise ValueError(
                    f"batch_size={batch_size} exceeds the per-worker item "
                    f"count {per_worker} ({max_items} items / {num_workers} "
                    f"workers / {shard[1]} shards); every batch would be "
                    "dropped. Lower batch_size/num_workers or pass "
                    "drop_last=False."
                )

    def __len__(self):
        _, num_shards = self.shard
        per_worker = self.dataset.max_items // (self.num_workers * num_shards)
        n, rem = divmod(per_worker, self.batch_size)
        if not self.drop_last and rem:
            n += 1
        return n * self.num_workers

    # -- worker machinery ---------------------------------------------------

    def _put(self, item):
        """Blocking put that aborts when the loader is being closed, so
        workers can never deadlock on a full queue nobody drains."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self, worker_id):
        shard_id, num_shards = self.shard
        try:
            # default collate delegates batching to the dataset: on the
            # native shm transport batches assemble straight out of the
            # ring arena (one copy, no per-item intermediates); otherwise
            # stream_batches falls back to stream()+collate internally.
            # The manual loop below remains for custom collate_fn and
            # stream()-only datasets.
            if self.collate_fn is default_collate and hasattr(
                self.dataset, "stream_batches"
            ):
                batches = self.dataset.stream_batches(
                    self.batch_size,
                    worker_id=worker_id,
                    num_workers=self.num_workers,
                    shard_id=shard_id,
                    num_shards=num_shards,
                    stop_event=self._stop,
                    drop_last=self.drop_last,
                    timer=self.timer,
                    arena_pool=self.arena_pool,
                )
                while True:
                    if self.gate is not None:
                        # next() does this worker's heavy lifting (ring
                        # drain + batch assembly): hold it at the boundary
                        # while a transfer owns the core; stop-aware so
                        # close() never waits out the gate backstop
                        self.gate.wait(stop=self._stop)
                    try:
                        out = next(batches)
                    except StopIteration:
                        break
                    if not self._put(out):
                        # stop raced the enqueue: the batch was already
                        # detached from the stream generator, so recycle
                        # its arena here or nobody will
                        if hasattr(out, "recycle"):
                            out.recycle()
                        return
                    if self._stop.is_set():
                        return
                self._put(_SENTINEL)
                return
            batch = []
            for item in self.dataset.stream(
                worker_id=worker_id,
                num_workers=self.num_workers,
                shard_id=shard_id,
                num_shards=num_shards,
                stop_event=self._stop,
            ):
                batch.append(item)
                if len(batch) == self.batch_size:
                    if self.gate is not None:
                        self.gate.wait(stop=self._stop)
                    with self.timer.stage("collate"):
                        out = self.collate_fn(batch)
                    batch = []
                    if not self._put(out):
                        return
                if self._stop.is_set():
                    return
            if batch and not self.drop_last:
                with self.timer.stage("collate"):
                    out = self.collate_fn(batch)
                if not self._put(out):
                    return
            self._put(_SENTINEL)
        except BaseException as exc:  # propagate to the consumer thread
            self._put(exc)

    def _start(self):
        self._started = True
        for w in range(self.num_workers):
            t = threading.Thread(
                target=self._worker, args=(w,), daemon=True, name=f"bjx-loader-{w}"
            )
            t.start()
            self._threads.append(t)

    def close(self):
        """Stop worker threads promptly (idempotent)."""
        self._stop.set()
        if sys.is_finalizing():
            # close() can run from generator finalization during interpreter
            # shutdown (abandoned iterator): the queue module is already torn
            # down and the daemon workers are dead — nothing to drain or join.
            return
        # drain so blocked put() calls can observe the stop flag; recycle
        # any arena batches stranded in the queue so a shared pool is not
        # starved by an early close
        try:
            while True:
                item = self._queue.get_nowait()
                if hasattr(item, "recycle"):
                    item.recycle()
        except queue.Empty:
            pass
        for t in self._threads:
            t.join(timeout=5)
        # keep hung workers visible instead of masking a leak
        self._threads = [t for t in self._threads if t.is_alive()]

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- consumer side ------------------------------------------------------

    def __iter__(self):
        if self._started:
            raise RuntimeError(
                "BatchLoader is single-use; create a new one per epoch/stream"
            )
        self._start()
        finished = 0
        try:
            while finished < self.num_workers:
                # timed get so a cross-thread close() (which stops workers
                # before their sentinels land) can't strand this consumer
                with self.timer.stage("recv"):
                    while True:
                        if self._stop.is_set():
                            return
                        try:
                            item = self._queue.get(timeout=0.1)
                            break
                        except queue.Empty:
                            continue
                if item is _SENTINEL:
                    finished += 1
                    continue
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            self.close()
