"""Vectorized remote environments — the env-pool abstraction the reference
never had (SURVEY.md §7 "hard parts": batching envs across processes for
vectorized policy training).

``EnvPool`` drives N Blender env instances in lockstep and exposes batched,
numpy-collated ``reset()``/``step(actions)`` whose outputs feed straight
into a jitted policy: stack of obs in, vector of actions out.  RPCs are
pipelined (send to all, then receive from all) so the wall-clock cost per
pool step is one frame of the slowest instance, not the sum.

``step`` auto-resets finished instances by default: an instance reporting
``done`` is sent ``reset`` on the *next* step and contributes its fresh
initial observation (its reward is 0 and done False for that transition) —
the standard vectorized-env contract (cf. gym vector envs), chosen so
policy rollouts under ``jax.jit``/``vmap`` see static shapes.

Fault tolerance (see docs/fault_tolerance.md): exchanges run under a
:class:`blendjax.btt.faults.FaultPolicy` (retries with backoff, per-call
deadline, per-env circuit breaker).  With ``quarantine=True`` (default) an
env that exhausts its retries is *quarantined* instead of failing the
whole batched step: it stops receiving RPCs, contributes a synthetic
transition (last known observation, zero reward, ``done=True`` exactly
once so trainers close the episode), and is flagged in the ``healthy``
mask / per-env infos.  Training continues on the N-1 live envs.
Quarantined envs are probed in the background of each ``step`` (or by a
:class:`blendjax.btt.supervise.FleetSupervisor`) with a fresh socket and a
``reset`` resync handshake; on success the env re-enters the pool through
the standard autoreset contract (fresh initial obs, zero reward).  Only
when *every* env is quarantined does ``step`` raise.

Async pipelined stepping (see docs/rl_stepping.md): ``step()`` is
lock-step — every call pays a full fan-out round trip plus the slowest
env's physics before any learner compute runs.  The
``step_async(actions)`` / ``step_wait(min_ready=k)`` pair overlaps the
two instead: requests ride DEALER sockets (empty-delimiter framing, so
the producers' REP sockets serve them unmodified) with per-request
correlation ids (``wire.BTMID_KEY``), up to ``pipeline_depth`` requests
in flight per env, and ``step_wait`` returns the first ``k`` completed
transitions *with their env indices* instead of blocking on stragglers.
The fault machinery covers the pipeline: in-flight requests age against
the policy deadline (retry -> re-send same correlation id, which the
producer agent dedupes -> quarantine), a quarantine mid-flight converts
that env's outstanding requests into synthetic transitions (the first
carrying the episode-closing ``done=True``) without touching survivors,
and re-admission resyncs the pipeline depth from zero.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from contextlib import contextmanager

import numpy as np
import zmq

from blendjax import wire
from blendjax.btt.collate import collate
from blendjax.btt.constants import DEFAULT_TIMEOUTMS
from blendjax.btt.env import kwargs_to_cli
from blendjax.btt.faults import FaultPolicy
from blendjax.obs.flight import flight_recorder
from blendjax.obs.spans import SpanRecorder, make_span, now_us
from blendjax.utils.timing import fleet_counters

logger = logging.getLogger("blendjax")


def _zero_like(obs):
    """Type/shape-preserving zero observation for a quarantined env that
    never delivered one (keeps batch collation static-shaped)."""
    if isinstance(obs, np.ndarray):
        return np.zeros_like(obs)
    if isinstance(obs, dict):
        return {k: _zero_like(v) for k, v in obs.items()}
    if isinstance(obs, (list, tuple)):
        seq = [_zero_like(v) for v in obs]
        return seq if isinstance(obs, list) else tuple(seq)
    if isinstance(obs, bool):
        return False
    if isinstance(obs, (int, float, complex, np.number)):
        return type(obs)(0)
    return obs


def _empty_batch_like(obs):
    """Zero-row batch matching ``collate``'s layout for samples shaped
    like ``obs``, so a timeout-expiry ``step_wait`` return concatenates
    cleanly with non-empty batches."""
    if isinstance(obs, np.ndarray):
        return np.empty((0,) + obs.shape, obs.dtype)
    if isinstance(obs, dict):
        return {k: _empty_batch_like(v) for k, v in obs.items()}
    if isinstance(obs, (list, tuple)):
        seq = [_empty_batch_like(v) for v in obs]
        return seq if isinstance(obs, list) else tuple(seq)
    if isinstance(obs, bool):
        return np.empty((0,), bool)
    if isinstance(obs, (int, float, complex, np.number)):
        return np.empty((0,), np.asarray(obs).dtype)
    return []


class EnvPool:
    """Batched client for N remote Blender environments.

    Params
    ------
    addresses: list[str]
        GYM endpoints, one per instance (e.g.
        ``launch_info.addresses['GYM']``).
    timeoutms: int
        Per-socket receive timeout (per-attempt wait when the fault
        policy sets no ``deadline_s``).
    autoreset: bool
        Auto-reset finished instances during ``step``.
    fault_policy: FaultPolicy | None
        Retry/backoff/circuit policy for exchanges and re-admission
        probes; None installs the default :class:`FaultPolicy`.  Pass
        ``FaultPolicy(max_retries=0)`` for strict single-attempt
        semantics (retrying ``step`` against a slow-but-alive env can
        advance it an extra frame — see :mod:`blendjax.btt.faults`).
    quarantine: bool
        Degraded mode: isolate failing envs and keep stepping the rest
        (see module docstring).  False restores fail-whole-batch:
        any env exhausting its retries raises ``TimeoutError`` naming it
        (successful siblings' ``env_times`` are committed first, so a
        partial exchange never desyncs the survivors).
    counters: EventCounters | None
        Fault-event sink; defaults to the process-wide
        ``blendjax.utils.timing.fleet_counters``.
    pipeline_depth: int
        Maximum requests in flight per env on the async
        ``step_async``/``step_wait`` path (>= 1).  Lock-step ``step()``
        ignores it.
    trace: bool
        Record cross-process trace spans (docs/observability.md): every
        RPC gets a client-side span in :attr:`spans` tagged with its
        ``wire.BTMID_KEY`` correlation id, requests carry a span
        context, and producer-side spans piggybacked on replies are
        ingested into the same recorder — one
        ``spans.export_chrome_trace(path)`` yields the merged
        multi-pid Perfetto timeline.  Off by default (zero per-RPC
        cost).
    span_recorder: SpanRecorder | None
        Share a recorder across components (implies ``trace=True``).
    """

    def __init__(
        self,
        addresses,
        timeoutms=DEFAULT_TIMEOUTMS,
        autoreset=True,
        fault_policy=None,
        quarantine=True,
        counters=None,
        pipeline_depth=1,
        trace=False,
        span_recorder=None,
    ):
        if pipeline_depth < 1:
            raise ValueError("pipeline_depth must be >= 1")
        if pipeline_depth > wire.REPLY_CACHE_DEPTH:
            # beyond the producer's dedupe window, a retried oldest
            # in-flight request can no longer be answered from its reply
            # cache — the frame would silently be simulated twice
            raise ValueError(
                f"pipeline_depth {pipeline_depth} exceeds the producer "
                f"reply-cache window ({wire.REPLY_CACHE_DEPTH}): retries "
                "could double-apply a non-idempotent step"
            )
        self._ctx = zmq.Context.instance()
        self._addresses = list(addresses)
        self._timeoutms = timeoutms
        self.sockets = [self._connect(a) for a in self._addresses]
        self.num_envs = len(self._addresses)
        self.env_times = [None] * self.num_envs
        self._needs_reset = np.ones(self.num_envs, dtype=bool)
        self.autoreset = autoreset
        self.quarantine = quarantine
        self.policy = fault_policy if fault_policy is not None else FaultPolicy()
        self.counters = counters if counters is not None else fleet_counters
        #: cross-process span sink (None = tracing off); producers'
        #: piggybacked spans land here next to the client-side ones
        self.spans = (
            span_recorder if span_recorder is not None
            else (SpanRecorder() if trace else None)
        )
        # quarantine state; _lock guards every transition (step runs on the
        # training thread, probes may run from a supervisor thread)
        self._lock = threading.RLock()
        self._exchanging = set()  # envs whose sockets a step/reset is using
        self._quarantined = np.zeros(self.num_envs, dtype=bool)
        self._states = [self.policy.new_state(i) for i in range(self.num_envs)]
        self._probe = [None] * self.num_envs  # per-env re-admission attempt
        self._fresh = [None] * self.num_envs  # unconsumed resync reset reply
        self._pending_done = set()  # envs owing their one quarantine done=True
        self._last_obs = [None] * self.num_envs
        # async pipeline state (step_async/step_wait).  DEALER channels are
        # dialed lazily — a pool that only ever uses lock-step step() never
        # opens them.  _dealer_stale marks channels that must be re-dialed
        # before reuse (set by quarantine from any thread; acted on only by
        # the async caller's thread, which owns the sockets).
        self.pipeline_depth = int(pipeline_depth)
        self._dealers = [None] * self.num_envs
        self._dealer_stale = [False] * self.num_envs
        # None until the env's first async reply; then whether the
        # producer echoes wire.BTMID_KEY.  Non-echoing (legacy) producers
        # fall back to FIFO reply matching, which a retry re-send would
        # corrupt (two mid-less replies for one record) — the aging pass
        # escalates their timeouts to failure instead of retrying
        self._mid_echo = [None] * self.num_envs
        self._inflight = [deque() for _ in range(self.num_envs)]
        self._ready = deque()  # completed transitions, completion order

    def _connect(self, addr):
        s = self._ctx.socket(zmq.REQ)
        s.setsockopt(zmq.LINGER, 0)
        s.setsockopt(zmq.SNDTIMEO, self._timeoutms * 10)
        s.setsockopt(zmq.RCVTIMEO, self._timeoutms)
        s.setsockopt(zmq.REQ_RELAXED, 1)
        s.setsockopt(zmq.REQ_CORRELATE, 1)
        s.connect(addr)
        return s

    def _dealer_socket(self, i):
        """The async channel for env ``i`` (lock held).  Re-dialed when
        stale — a quarantine marks the channel dirty so replies belonging
        to the pre-quarantine pipeline can never poison the re-admitted
        env; only the async caller's thread (which owns the sockets)
        actually closes/re-dials, keeping zmq single-threaded."""
        s = self._dealers[i]
        if s is None or self._dealer_stale[i]:
            if s is not None:
                s.close(0)
            s = self._ctx.socket(zmq.DEALER)
            # no SNDTIMEO/RCVTIMEO: every dealer send/recv is non-blocking
            # (DONTWAIT + Poller), so socket timeouts would be inert
            s.setsockopt(zmq.LINGER, 0)
            s.connect(self._addresses[i])
            self._dealers[i] = s
            self._dealer_stale[i] = False
        return s

    # -- health surface -----------------------------------------------------

    @property
    def healthy(self):
        """Boolean mask, True for envs currently serving real transitions."""
        with self._lock:
            return ~self._quarantined.copy()

    @property
    def quarantined(self):
        with self._lock:
            return self._quarantined.copy()

    @property
    def inflight(self):
        """Per-env count of async requests currently in flight."""
        with self._lock:
            return [len(dq) for dq in self._inflight]

    # -- pipelined RPC ------------------------------------------------------

    def _recv_wait_ms(self):
        """Per-attempt recv wait: the policy deadline when set (so one
        slow env cannot eat the whole socket timeout per attempt), else
        the socket timeout."""
        if self.policy.deadline_s is not None:
            return max(1, int(self.policy.deadline_s * 1000))
        return self._timeoutms

    def _exchange(self, requests, indices=None):
        """Pipelined exchange over env ``indices`` (default: all).

        Sends every request, then collects replies; an env that fails its
        send or exhausts its recv retries lands in ``failed`` instead of
        aborting the exchange, and every *successful* reply commits its
        ``env_times`` entry regardless of sibling failures (a partial
        exchange must never desync the survivors).

        Returns ``(replies, failed)``: ``replies`` maps env index to its
        reply dict, ``failed`` maps env index to the error string.
        """
        if indices is None:
            indices = list(range(self.num_envs))
        # socket mutual exclusion with the probe machinery, both ways: an
        # env quarantined between the caller's snapshot and this point may
        # have a probe mid-flight on its (re-dialed) socket, and a probe
        # must never touch a socket this exchange is using.  Quarantined /
        # busy-probed envs are failed up front without an RPC.
        with self._lock:
            blocked = {
                i for i in indices
                if self._quarantined[i]
                or (self._probe[i] is not None and self._probe[i].get("busy"))
            }
            self._exchanging = set(indices) - blocked
        try:
            return self._exchange_locked_out(requests, indices, blocked)
        finally:
            with self._lock:
                self._exchanging = set()

    def _exchange_locked_out(self, requests, indices, blocked=()):
        reqs = dict(zip(indices, requests))
        # stamp once per logical call: a policy-driven re-send below
        # carries the SAME id, so a blendjax producer that already
        # simulated the frame re-serves its cached reply instead of
        # stepping twice (the id is echoed in the reply and popped on
        # receive, so lock-step results stay bit-identical)
        for req in reqs.values():
            mid = wire.stamp_message_id(req)
            if self.spans is not None:
                wire.stamp_span_context(req, mid)
        t0_us = {}  # per-env client-span start (tracing only)
        replies, failed = {}, {}
        awaiting = []
        for i in indices:
            if i in blocked:
                failed[i] = f"environment {i} is quarantined"
                continue
            if self._states[i].circuit_open():
                # the breaker protects strict-mode pools too: a dead env
                # stops costing (max_retries+1) recv waits per step
                self.counters.incr("circuit_rejections")
                failed[i] = (
                    f"environment {i} circuit open after "
                    f"{self._states[i].consecutive_failures} consecutive "
                    "failures"
                )
                continue
            if self.spans is not None:
                # BEFORE the send: the producer stamps its span at
                # request receipt, which can precede this thread's next
                # instruction once the zmq enqueue is out — a t0 taken
                # after the send would let the producer span escape its
                # enclosing client span
                t0_us[i] = now_us()
            try:
                wire.send_message(self.sockets[i], reqs[i])
                awaiting.append(i)
            except zmq.Again:
                self.counters.incr("timeouts")
                self._states[i].record_failure(self.counters)
                failed[i] = f"send to environment {i} timed out"
        # recv phase: one poller over every awaiting socket, in rounds —
        # attempt r waits at most one recv budget for ALL still-pending
        # envs together, so K simultaneously dead envs stall a step for
        # ~(max_retries+1) recv waits total, not K times that
        wait_ms = self._recv_wait_ms()
        pending = set(awaiting)
        poller = zmq.Poller()
        for i in pending:
            poller.register(self.sockets[i], zmq.POLLIN)
        for attempt in range(self.policy.max_retries + 1):
            deadline = time.monotonic() + wait_ms / 1e3
            while pending:
                remaining_ms = int((deadline - time.monotonic()) * 1000)
                if remaining_ms <= 0:
                    break
                events = dict(poller.poll(remaining_ms))
                if not events:
                    break
                for i in list(pending):
                    sock = self.sockets[i]
                    if not (events.get(sock, 0) & zmq.POLLIN):
                        continue
                    try:
                        ddict = wire.recv_message(sock)
                    except Exception:
                        # a garbled/unpicklable reply is an env fault,
                        # not a pool crash: discard it and let the retry
                        # / quarantine machinery handle the env
                        logger.warning(
                            "env %d: malformed reply discarded", i,
                            exc_info=True,
                        )
                        continue
                    piggyback = wire.pop_spans(ddict)
                    ddict.pop(wire.BTMID_KEY, None)
                    if self.spans is not None:
                        self.spans.ingest(piggyback)
                        self.spans.record(make_span(
                            "env_rpc", t0_us.get(i, now_us()),
                            trace=reqs[i].get(wire.BTMID_KEY),
                            cat="envpool", args={"env": i},
                        ))
                    self.env_times[i] = ddict.get("time")
                    self._states[i].record_success()
                    replies[i] = ddict
                    poller.unregister(sock)
                    pending.discard(i)
            if not pending:
                break
            for i in pending:
                self.counters.incr("timeouts")
                self._states[i].record_failure(self.counters)
            if attempt >= self.policy.max_retries:
                for i in pending:
                    self.counters.incr("failures")
                    failed[i] = (
                        f"no response from environment {i} within timeout"
                    )
                break
            # one shared backoff per round (the slowest of the pending
            # envs' jittered delays), then re-send to all of them —
            # REQ_RELAXED allows it, REQ_CORRELATE drops the stale reply
            self.counters.incr("retries", len(pending))
            delay = max(
                self._states[i].backoff(attempt + 1) for i in pending
            )
            if delay > 0:
                time.sleep(delay)
            for i in list(pending):
                try:
                    wire.send_message(self.sockets[i], reqs[i])
                except zmq.Again:
                    self.counters.incr("failures")
                    failed[i] = f"send to environment {i} timed out"
                    poller.unregister(self.sockets[i])
                    pending.discard(i)
        return replies, failed

    def _fail_or_quarantine(self, failed):
        """Route exchange failures: quarantine mode isolates each failed
        env; strict mode raises (after the successes were committed)."""
        if not failed:
            return
        if not self.quarantine:
            raise TimeoutError("; ".join(failed.values()))
        for i, reason in failed.items():
            self.quarantine_env(i, reason=reason)

    # -- quarantine & re-admission ------------------------------------------

    def quarantine_env(self, i, reason="unresponsive"):
        """Isolate env ``i``: no more RPCs until a probe re-admits it.
        Idempotent; safe from any thread (the supervisor calls this
        proactively on producer death, ahead of any timeout).

        A quarantine mid-flight drains the env's async pipeline: every
        outstanding request it owed a transition for becomes a synthetic
        ready transition (the first carrying the episode's one
        ``done=True``), and the DEALER channel is marked stale so its
        possible late replies are orphaned rather than delivered to the
        re-admitted incarnation."""
        with self._lock:
            if self._quarantined[i]:
                return
            self._quarantined[i] = True
            self._pending_done.add(i)
            self._fresh[i] = None
            self._probe[i] = {"active": False, "sent": False, "started": 0.0,
                              "attempts": 0, "next_at": 0.0}
            self.counters.incr("quarantines")
            owed = sum(1 for r in self._inflight[i] if not r["discard"])
            if self._inflight[i]:
                self.counters.incr("inflight_discards", len(self._inflight[i]))
                self._inflight[i].clear()
                self._dealer_stale[i] = True
            for _ in range(owed):
                self._ready.append(self._synthetic_ready_locked(i))
        flight_recorder.note("quarantine", target=f"env{i}", reason=reason)
        logger.warning("env %d quarantined: %s", i, reason)

    def notify_respawn(self, i):
        """The producer behind env ``i`` was restarted: drop the backoff
        and circuit state so the next probe runs immediately on a fresh
        socket (called by :class:`~blendjax.btt.supervise.FleetSupervisor`
        after a watchdog respawn)."""
        with self._lock:
            if not self._quarantined[i]:
                return
            self._states[i] = self.policy.new_state(i)
            p = self._probe[i]
            if p is not None and p.get("busy"):
                # a probe is mid-flight on this env's socket from another
                # thread: don't replace its attempt record (a fresh one
                # would let a second probe redial — and close — the
                # socket in use); just clear the backoff so the next
                # attempt after it resolves runs immediately
                p.update(next_at=0.0, attempts=0)
            else:
                self._probe[i] = {"active": False, "sent": False,
                                  "started": 0.0, "attempts": 0,
                                  "next_at": 0.0}

    def probe(self, block_ms=0):
        """Attempt re-admission of quarantined envs (backoff/circuit
        gated).  Each attempt is a three-phase async handshake spread over
        successive calls — dial a fresh socket, send a ``reset`` resync
        once the connection is writable, collect the fresh initial
        observation — so ``block_ms=0`` (the in-``step`` mode) never
        blocks the training loop; positive ``block_ms`` bounds each wait
        (supervisor heal loop).  An attempt that exceeds the policy
        deadline fails, feeds the circuit breaker, and backs off.
        Returns the list of env indices re-admitted by this call."""
        readmitted = []
        deadline_s = (
            self.policy.deadline_s
            if self.policy.deadline_s is not None
            else self._timeoutms / 1e3
        )
        # phase 1 (locked, non-blocking): pick due probes, dial fresh
        # sockets, and mark each one busy so concurrent probe callers
        # (training step vs supervisor heal thread) never share a socket
        work = []
        with self._lock:
            if not self.sockets:
                return readmitted  # pool closed (a heal tick may race it)
            now = time.monotonic()
            for i in np.flatnonzero(self._quarantined):
                i = int(i)
                st, p = self._states[i], self._probe[i]
                if p is None or p.get("busy") or i in self._exchanging:
                    continue
                if st.circuit_open(now) or now < p["next_at"]:
                    continue
                if not p.get("active"):
                    # reconnect: a fresh REQ drops any half-done request
                    # cycle and re-dials the (possibly re-bound) endpoint
                    self.sockets[i].close(0)
                    self.sockets[i] = self._connect(self._addresses[i])
                    p.update(active=True, sent=False, started=now)
                p["busy"] = True
                work.append((i, self.sockets[i], p))
        # phase 2 (unlocked): the blocking polls — a dead endpoint must
        # not starve step()/reset() of the pool lock while we wait on it
        for i, sock, p in work:
            reply, malformed = None, False
            try:
                if not p["sent"] and sock.poll(block_ms, zmq.POLLOUT):
                    try:
                        wire.send_message(
                            sock, {"cmd": "reset", "time": None},
                            flags=zmq.NOBLOCK,
                        )
                        p["sent"] = True
                    except zmq.Again:
                        pass  # connection raced away; retry within deadline
                if p["sent"] and sock.poll(block_ms, zmq.POLLIN):
                    try:
                        reply = wire.recv_message(sock)
                    except Exception:
                        malformed = True
                        logger.warning(
                            "env %d: malformed resync reply discarded", i,
                            exc_info=True,
                        )
            finally:
                # phase 3 (locked): apply the outcome
                with self._lock:
                    p["busy"] = False
                    if reply is not None and self._quarantined[i]:
                        self.env_times[i] = reply.get("time")
                        self._fresh[i] = reply
                        self._quarantined[i] = False
                        self._needs_reset[i] = False
                        self._probe[i] = None
                        # an unsurfaced quarantine done stays pending:
                        # step() emits the interrupted episode's terminal
                        # transition before consuming the resync obs
                        self._states[i].record_success()
                        self.counters.incr("readmissions")
                        readmitted.append(i)
                        flight_recorder.note(
                            "readmission", target=f"env{i}"
                        )
                        logger.warning("env %d re-admitted after resync", i)
                    elif malformed or (
                        time.monotonic() - p["started"] >= deadline_s
                    ):
                        self.counters.incr("timeouts")
                        self._probe_failed(i, time.monotonic())
        return readmitted

    def _probe_failed(self, i, now):
        """One re-admission attempt failed: back off (policy jitter) and
        schedule a fresh-socket retry; consecutive failures feed the
        circuit breaker so a permanently-dead endpoint stops being dialed
        every step."""
        p = self._probe[i]
        p["attempts"] += 1
        p["active"] = False
        self._states[i].record_failure(self.counters)
        p["next_at"] = now + self._states[i].backoff(p["attempts"])

    # -- batched API --------------------------------------------------------

    def reset(self):
        """Reset all live instances; returns ``(batched_obs, infos)``.

        Quarantined envs contribute their last known (or zero) observation
        with ``info['healthy'] = False``; they rejoin via the re-admission
        handshake, which itself performs a ``reset``.  Raises when every
        env is quarantined.

        An explicit reset supersedes any async pipeline in progress: all
        in-flight requests and uncollected ready transitions are
        discarded and the DEALER channels marked for re-dial.
        """
        self.probe(block_ms=0)
        with self._lock:
            self._fresh = [None] * self.num_envs  # superseded by this reset
            for i in range(self.num_envs):
                if self._inflight[i]:
                    self.counters.incr(
                        "inflight_discards", len(self._inflight[i])
                    )
                    self._inflight[i].clear()
                    self._dealer_stale[i] = True
            self._ready.clear()
            live = [i for i in range(self.num_envs) if not self._quarantined[i]]
        if not live:
            raise TimeoutError("all environments are quarantined")
        if not self.quarantine and len(live) < self.num_envs:
            # strict mode: a supervisor-quarantined env fails the call
            # instead of contributing a synthetic slot
            raise TimeoutError(
                "environment(s) "
                f"{[i for i in range(self.num_envs) if i not in live]} are "
                "quarantined (strict mode: no degraded batches)"
            )
        replies, failed = self._exchange(
            [{"cmd": "reset", "time": self.env_times[i]} for i in live],
            indices=live,
        )
        self._fail_or_quarantine(failed)
        if not replies:
            # the exchange in which the LAST live envs fail must raise,
            # not return an all-synthetic batch (which, before any env
            # ever delivered an obs, couldn't even be shaped correctly)
            raise TimeoutError(
                "all environments are quarantined: "
                + "; ".join(failed.values())
            )
        # commit every live obs BEFORE assembly so a quarantined slot can
        # synthesize a shape-matched placeholder even on the first batch
        for j, r in replies.items():
            self._last_obs[j] = r.pop("obs")
        obs, infos = [], []
        for i in range(self.num_envs):
            r = replies.get(i)
            if r is not None:
                self._needs_reset[i] = False
                # an explicit reset IS the episode boundary; any owed
                # quarantine done for this env is thereby delivered
                self._pending_done.discard(i)
                r.pop("rgb_array", None)
                r["healthy"] = True
                obs.append(self._last_obs[i])
            else:
                obs.append(self._synthetic_obs(i))
                r = {"healthy": False, "quarantined": True}
            infos.append(r)
        return collate(obs), infos

    def _readmission_entry_locked(self, i):
        """Arbitrate the re-admission race for env ``i`` (lock held) and
        return its completed transition, or ``None`` when no unconsumed
        resync reply is waiting.

        When re-admission won the race with the training loop, the
        interrupted episode's terminal transition (``done=True`` on the
        last real obs) must still surface exactly once — it is emitted
        NOW and the fresh resync obs stays held for the next
        consumption.  Otherwise the resync observation surfaces through
        the autoreset contract (``readmitted=True``, zero reward).  Both
        lock-step ``step()`` and ``step_async`` route re-admission
        through here so the race arbitration can never diverge between
        the two modes.
        """
        if self._fresh[i] is None or self._quarantined[i]:
            return None
        if i in self._pending_done:
            self._pending_done.discard(i)
            self._needs_reset[i] = False
            return {
                "env": i, "obs": self._synthetic_obs(i), "reward": 0.0,
                "done": True,
                "info": {"healthy": True, "quarantined": True,
                         "interrupted": True},
            }
        f = self._fresh[i]
        self._fresh[i] = None
        self._last_obs[i] = f.pop("obs")
        f.pop("rgb_array", None)
        f.pop(wire.BTMID_KEY, None)
        f.pop(wire.SPANS_KEY, None)
        f.update(healthy=True, readmitted=True)
        self._needs_reset[i] = False
        return {
            "env": i, "obs": self._last_obs[i], "reward": 0.0,
            "done": False, "info": f,
        }

    def step(self, actions):
        """Step all instances with a length-N batch of actions.

        Returns ``(obs, rewards, dones, infos)`` with obs collated and
        rewards/dones as float32/bool arrays.  With ``autoreset``,
        instances that reported done on the previous step are reset now.

        Under quarantine, isolated envs return synthetic transitions
        (``info['healthy'] = False``) and freshly re-admitted envs return
        their resync observation through the autoreset contract
        (``info['readmitted'] = True``, zero reward).
        """
        if len(actions) != self.num_envs:
            raise ValueError(f"expected {self.num_envs} actions, got {len(actions)}")
        if any(self._inflight) or self._ready:
            # the two modes share env_times/needs_reset state and must not
            # interleave: the producers' REP sockets fair-queue across the
            # REQ and DEALER connections, so a lock-step request could
            # overtake queued pipeline requests and desync the clocks
            raise RuntimeError(
                "lock-step step() called with async requests in flight; "
                "drain them with step_wait() (or reset()) first"
            )
        self.probe(block_ms=0)
        with self._lock:
            quarantined = self._quarantined.copy()
            # env -> completed re-admission transition (the fresh resync
            # obs, or the interrupted episode's owed terminal), consumed
            # ahead of the exchange exactly as the async path does
            pre = {}
            for i in range(self.num_envs):
                entry = self._readmission_entry_locked(i)
                if entry is not None:
                    pre[i] = entry
        if quarantined.all():
            raise TimeoutError("all environments are quarantined")
        if not self.quarantine and quarantined.any():
            # strict mode never serves synthetic transitions — a
            # supervisor (or caller) may still quarantine_env() on
            # producer death, and the strict caller opted to fail instead
            # of training on fabricated data
            raise TimeoutError(
                "environment(s) "
                f"{[int(i) for i in np.flatnonzero(quarantined)]} are "
                "quarantined (strict mode: no degraded batches)"
            )
        send_idx, requests = [], []
        for i, action in enumerate(actions):
            if quarantined[i] or i in pre:
                continue
            send_idx.append(i)
            if self.autoreset and self._needs_reset[i]:
                requests.append({"cmd": "reset", "time": self.env_times[i]})
            else:
                requests.append(
                    {"cmd": "step", "action": action, "time": self.env_times[i]}
                )
        replies, failed = self._exchange(requests, indices=send_idx)
        self._fail_or_quarantine(failed)
        if not replies and not pre:
            # every remaining live env failed in THIS call: raise rather
            # than hand back a batch with no real transition in it
            raise TimeoutError(
                "all environments are quarantined: "
                + "; ".join(failed.values())
            )
        with self._lock:
            quarantined = self._quarantined.copy()
            # an env owes its one quarantine done=True only while it is
            # actually served synthetically: a reply that raced the
            # quarantine keeps its real transition, and a slot being served
            # from `pre` this step emits its own bookkeeping — in every
            # excluded case the pending done survives and fires on that
            # env's next synthetic step instead of vanishing
            q_done = {
                i for i in self._pending_done
                if quarantined[i]
                and i not in replies
                and i not in pre
            }
            self._pending_done -= q_done

        # commit every live obs BEFORE assembly so a quarantined slot can
        # synthesize a shape-matched placeholder even on the first batch
        # (re-admission obs were committed by _readmission_entry_locked)
        for j, r in replies.items():
            self._last_obs[j] = r.pop("obs")
        obs, rewards, dones, infos = [], [], [], []
        for i in range(self.num_envs):
            r = replies.get(i)
            if i in pre:
                e = pre[i]
                obs.append(e["obs"])
                rewards.append(e["reward"])
                dones.append(e["done"])
                infos.append(e["info"])
            elif r is not None:
                was_reset = self.autoreset and self._needs_reset[i]
                obs.append(self._last_obs[i])
                rewards.append(0.0 if was_reset else float(r.pop("reward", 0.0)))
                done = False if was_reset else bool(r.pop("done", False))
                dones.append(done)
                self._needs_reset[i] = done
                r.pop("rgb_array", None)
                r["healthy"] = True
                infos.append(r)
            else:
                obs.append(self._synthetic_obs(i))
                rewards.append(0.0)
                dones.append(i in q_done)
                self._needs_reset[i] = False
                infos.append({"healthy": False, "quarantined": True})
        return (
            collate(obs),
            np.asarray(rewards, np.float32),
            np.asarray(dones, bool),
            infos,
        )

    # -- async pipelined API ------------------------------------------------
    #
    # step_async/step_wait overlap env physics with learner compute: a
    # producer with a queued request simulates its next frame while the
    # consumer is still processing the previous reply, so the steady-state
    # cost per transition is max(physics, consumer work) instead of
    # RTT + physics + consumer work.  The pair is single-consumer: call it
    # from one thread (quarantine/probe traffic from a supervisor thread
    # remains safe, as with lock-step step()).

    def step_async(self, actions, indices=None):
        """Submit one request per env without waiting for replies.

        Params
        ------
        actions:
            One action per target env.  Without ``indices``, must have
            length ``num_envs`` (one submission per env); with
            ``indices``, ``actions[j]`` goes to env ``indices[j]`` —
            repeating an index submits several requests to that env
            (bounded by ``pipeline_depth`` outstanding).
        indices: iterable[int] | None
            Target envs; the natural argument is the index array the
            previous ``step_wait`` returned, which keeps every env's
            pipeline at constant depth.

        Every submission eventually yields exactly one transition from
        ``step_wait``: live envs answer with real transitions;
        quarantined envs (and envs that fail mid-flight) yield synthetic
        ones; a freshly re-admitted env yields its resync observation
        through the autoreset contract.  ONE exception: requests already
        queued behind an episode's terminal ``done`` carry post-terminal
        frames and are consumed silently (counted in
        ``inflight_discards`` and reported as
        ``info['inflight_discarded']`` on the terminal transition) — a
        constant-depth driver should resubmit that many extra actions to
        the env to keep its pipeline full across episode boundaries.
        With ``autoreset``, an env whose last collected transition was
        ``done`` is sent ``reset`` instead of ``step``.  Raises
        ``TimeoutError`` when every env is quarantined (or, strict mode,
        when any is) and ``RuntimeError`` when an env's pipeline is
        already at ``pipeline_depth``.
        """
        if indices is None:
            if len(actions) != self.num_envs:
                raise ValueError(
                    f"expected {self.num_envs} actions, got {len(actions)}"
                )
            indices = range(self.num_envs)
        else:
            indices = [int(i) for i in indices]
            if len(actions) != len(indices):
                raise ValueError(
                    f"expected {len(indices)} actions for {len(indices)} "
                    f"indices, got {len(actions)}"
                )
        self.probe(block_ms=0)
        wait_s = self._recv_wait_ms() / 1e3
        failed = {}  # env -> reason (for quarantine/strict routing)
        failed_counts = {}  # env -> failed submissions (owed synthetics)
        with self._lock:
            if self._quarantined.all():
                raise TimeoutError("all environments are quarantined")
            if not self.quarantine and self._quarantined.any():
                raise TimeoutError(
                    "environment(s) "
                    f"{[int(i) for i in np.flatnonzero(self._quarantined)]} "
                    "are quarantined (strict mode: no degraded batches)"
                )
            for i, action in zip(indices, actions):
                entry = self._readmission_entry_locked(i)
                if entry is not None:
                    self._ready.append(entry)
                    continue
                if self._quarantined[i]:
                    self._ready.append(self._synthetic_ready_locked(i))
                    continue
                live = sum(
                    1 for r in self._inflight[i] if not r["discard"]
                )
                if live >= self.pipeline_depth:
                    raise RuntimeError(
                        f"environment {i} already has {live} requests in "
                        f"flight (pipeline_depth={self.pipeline_depth})"
                    )
                if len(self._inflight[i]) >= wire.REPLY_CACHE_DEPTH:
                    # discard-marked post-terminal frames still occupy
                    # the producer's dedupe window; outrunning it would
                    # let a retry double-simulate a frame
                    raise RuntimeError(
                        f"environment {i} has "
                        f"{len(self._inflight[i])} requests outstanding, "
                        f"the producer dedupe window "
                        f"(wire.REPLY_CACHE_DEPTH={wire.REPLY_CACHE_DEPTH});"
                        " collect transitions before resubmitting"
                    )
                if self._states[i].circuit_open():
                    self.counters.incr("circuit_rejections")
                    failed[i] = (
                        f"environment {i} circuit open after "
                        f"{self._states[i].consecutive_failures} consecutive "
                        "failures"
                    )
                    failed_counts[i] = failed_counts.get(i, 0) + 1
                    continue
                if self.autoreset and self._needs_reset[i]:
                    request = {"cmd": "reset", "time": self.env_times[i]}
                    # optimistic flip: a depth>1 caller submitting again
                    # before collecting must not queue a second reset
                    self._needs_reset[i] = False
                else:
                    request = {
                        "cmd": "step", "action": action,
                        "time": self.env_times[i],
                    }
                mid = wire.stamp_message_id(request)
                if self.spans is not None:
                    wire.stamp_span_context(request, mid)
                # span start BEFORE the send: the producer stamps its
                # span at receipt, which can precede our next
                # instruction once the zmq enqueue is out
                t0_us = now_us() if self.spans is not None else 0
                now = time.monotonic()
                try:
                    wire.send_message_dealer(
                        self._dealer_socket(i), request, flags=zmq.DONTWAIT
                    )
                except zmq.Again:
                    self.counters.incr("timeouts")
                    self._states[i].record_failure(self.counters)
                    failed[i] = f"send to environment {i} timed out"
                    failed_counts[i] = failed_counts.get(i, 0) + 1
                    continue
                self._inflight[i].append({
                    "mid": mid, "cmd": request["cmd"], "request": request,
                    "sent_at": now, "expires_at": now + wait_s,
                    "attempt": 0, "discard": False, "reply": None,
                    "t0_us": t0_us,
                })
        self._fail_or_quarantine(failed)  # strict mode raises here
        if failed_counts:
            # each failed submission still owes its transition — counted
            # per submission, since a repeated index can fail twice; the
            # quarantine above synthesized only previously-outstanding ones
            with self._lock:
                for i, n in failed_counts.items():
                    for _ in range(n):
                        self._ready.append(self._synthetic_ready_locked(i))

    def step_wait(self, min_ready=None, timeout_ms=None):
        """Collect completed transitions, ready-first.

        Blocks until at least ``min_ready`` transitions are available
        (default: every transition currently owed — full barrier), then
        returns ALL completed ones as ``(indices, obs, rewards, dones,
        infos)`` where ``indices`` maps each row to its env (an env at
        depth > 1 may contribute several rows, oldest first; per-env
        ordering is preserved).  ``min_ready`` is clamped to the number
        of transitions actually owed, so a partially-submitted pool can
        never deadlock.  ``timeout_ms`` bounds the wait: on expiry
        whatever is ready is returned (possibly zero rows).

        Failure semantics match ``step()``: an in-flight request that
        exhausts the policy's retries quarantines its env (the owed
        transitions arrive synthetically) or, with ``quarantine=False``,
        raises a ``TimeoutError`` naming it — already-completed
        transitions stay queued for the next ``step_wait``.
        """
        return self._assemble_ready(
            self._step_wait_entries(min_ready, timeout_ms)
        )

    def _step_wait_entries(self, min_ready, timeout_ms):
        """The ready-first collection loop; returns raw entry dicts."""
        deadline = (
            None if timeout_ms is None
            else time.monotonic() + timeout_ms / 1e3
        )
        wait_s = self._recv_wait_ms() / 1e3
        waited = False
        while True:
            with self._lock:
                pending = [
                    i for i in range(self.num_envs) if self._inflight[i]
                ]
                expected = len(self._ready) + sum(
                    1 for i in pending for r in self._inflight[i]
                    if not r["discard"]
                )
                target = (
                    expected if min_ready is None
                    else min(int(min_ready), expected)
                )
                # the full barrier also waits out discard-marked records
                # (post-terminal frames, no row owed): it must leave the
                # pool quiesced — step_wait() is lock-step step()'s
                # documented remedy, so it cannot strand replies in flight
                complete = (
                    not pending if min_ready is None
                    else len(self._ready) >= target
                )
                if complete:
                    out = list(self._ready)
                    self._ready.clear()
                    return out
                socks = {i: self._dealers[i] for i in pending
                         if self._dealers[i] is not None
                         and not self._dealer_stale[i]}
                # stashed-reply records are complete (held only for
                # in-order surfacing): never let their old deadlines zero
                # the poll budget.  Non-empty: a queue head is always
                # reply-less, else it would have surfaced.
                next_expiry = min(
                    r["expires_at"]
                    for i in pending for r in self._inflight[i]
                    if r.get("reply") is None
                )
            # fast path: drain replies already sitting in the zmq queues
            # (the steady pipelined state — producers run ahead of the
            # consumer) without paying for a Poller + poll syscall
            if self._drain_async_replies(socks):
                continue  # re-check the target before blocking
            if not waited:
                waited = True
                self.counters.incr("ready_waits")
            # poll outside the lock: a slow env must not starve the
            # supervisor's probe/quarantine machinery
            now = time.monotonic()
            budget_s = next_expiry - now
            if deadline is not None:
                budget_s = min(budget_s, deadline - now)
            # bounded park: a supervisor-thread quarantine_env() completes
            # owed transitions straight into _ready, and nothing on the
            # (dead) sockets would wake the poll — slice the wait so a
            # proactive quarantine surfaces within ~50 ms, not the full
            # recv budget
            budget_s = min(budget_s, 0.05)
            if socks and budget_s > 0:
                poller = zmq.Poller()
                for s in socks.values():
                    poller.register(s, zmq.POLLIN)
                if poller.poll(max(1, int(budget_s * 1000))):
                    self._drain_async_replies(socks)
            elif not socks:
                # every pending env's channel is stale (quarantined
                # mid-wait): loop back and let the bookkeeping settle
                time.sleep(0.001)
            # age the in-flight requests against the policy deadline
            now = time.monotonic()
            failed = {}
            with self._lock:
                for i in pending:
                    if self._quarantined[i]:
                        continue
                    for rec in list(self._inflight[i]):
                        if rec.get("reply") is not None:
                            continue  # complete, held for in-order surfacing
                        if rec["expires_at"] > now:
                            continue
                        self.counters.incr("timeouts")
                        self._states[i].record_failure(self.counters)
                        if (rec["attempt"] >= self.policy.max_retries
                                or self._mid_echo[i] is False):
                            # legacy producer (no correlation-id echo): a
                            # re-send would be simulated as a SECOND frame
                            # and its extra mid-less reply would shift the
                            # FIFO fallback matching off by one for every
                            # later transition — escalate instead of
                            # retrying
                            self.counters.incr("failures")
                            failed[i] = (
                                f"no response from environment {i} within "
                                "timeout"
                                + ("" if self._mid_echo[i] is not False else
                                   " (producer echoes no correlation id: "
                                   "retries unsafe on the pipelined path)")
                            )
                            break
                        rec["attempt"] += 1
                        self.counters.incr("retries")
                        try:
                            # same correlation id: a producer that already
                            # simulated the frame re-serves its cached
                            # reply instead of stepping twice
                            wire.send_message_dealer(
                                self._dealer_socket(i), rec["request"],
                                flags=zmq.DONTWAIT,
                            )
                        except zmq.Again:
                            self.counters.incr("failures")
                            failed[i] = f"send to environment {i} timed out"
                            break
                        rec["expires_at"] = (
                            now + wait_s
                            + self._states[i].backoff(rec["attempt"])
                        )
            self._fail_or_quarantine(failed)  # strict mode raises here
            if deadline is not None and time.monotonic() >= deadline:
                with self._lock:
                    out = list(self._ready)
                    self._ready.clear()
                return out

    def _drain_async_replies(self, socks):
        """NOBLOCK-receive every queued reply on ``socks``; returns the
        number of messages consumed (0 = nothing was waiting)."""
        drained = 0
        failed = {}
        for i, s in socks.items():
            while True:
                try:
                    ddict = wire.recv_message_dealer(s, flags=zmq.NOBLOCK)
                except zmq.Again:
                    break
                except Exception:
                    # a garbled/unpicklable reply is an env fault: let
                    # the deadline/retry machinery deal with the env
                    logger.warning(
                        "env %d: malformed reply discarded", i,
                        exc_info=True,
                    )
                    continue
                reason = self._process_async_reply(i, ddict)
                drained += 1
                if reason is not None:
                    failed[i] = reason
                    break
        self._fail_or_quarantine(failed)  # strict mode raises here
        return drained

    def step_wait_full(self, timeout_ms=None):
        """Barrier variant of :meth:`step_wait` shaped like ``step()``:
        waits for every owed transition and returns ``(obs, rewards,
        dones, infos)`` in env order, one row per env.  Requires each env
        to owe exactly one transition (the ``step_async(actions)``
        full-batch pattern); extra rows from a deeper pipeline stay
        queued for the next wait, and an env owing none raises."""
        entries = self._step_wait_entries(None, timeout_ms)
        first = {}
        leftover = []
        for entry in entries:
            if entry["env"] in first:
                leftover.append(entry)
            else:
                first[entry["env"]] = entry
        missing = [i for i in range(self.num_envs) if i not in first]
        if missing:
            # put everything back (original order) before failing: the
            # collected rows may include terminal transitions an env will
            # never re-emit
            with self._lock:
                for entry in reversed(entries):
                    self._ready.appendleft(entry)
                unsubmitted = [i for i in missing if not self._inflight[i]]
            if unsubmitted:
                raise RuntimeError(
                    "step_wait_full: no transition owed by env(s) "
                    f"{unsubmitted}; submit with step_async(actions) first"
                )
            # every missing env still has its request in flight: the
            # timeout_ms deadline expired, not an unsubmitted pool
            raise TimeoutError(
                f"step_wait_full: timed out waiting on env(s) {missing} "
                "(requests still in flight; collected rows requeued)"
            )
        if leftover:
            # deeper-pipeline extras go back to the ready queue, order kept
            with self._lock:
                for entry in reversed(leftover):
                    self._ready.appendleft(entry)
        ordered = [first[i] for i in range(self.num_envs)]
        return (
            collate([e["obs"] for e in ordered]),
            np.asarray([e["reward"] for e in ordered], np.float32),
            np.asarray([e["done"] for e in ordered], bool),
            [e["info"] for e in ordered],
        )

    def _process_async_reply(self, i, ddict):
        """Match a reply to its in-flight record, then surface completed
        records strictly in submission order.

        A reply that overtakes a lost older one (drop/garble chaos ate
        the older reply on the wire — a healthy DEALER<->REP channel is
        FIFO, so a gap means loss) is stashed on its record, the older
        requests are immediately re-sent under their original correlation
        ids (the producer's reply cache answers without simulating the
        frames twice), and everything surfaces once the head of the queue
        is complete — per-env ordering and the one-transition-per-
        submission invariant both hold through reply loss.

        Returns ``None``, or a failure-reason string when the env must
        be failed/quarantined by the caller (a producer revealed itself
        as non-echoing AFTER a retry already went out — the FIFO
        fallback can no longer attribute replies safely)."""
        mid = ddict.pop(wire.BTMID_KEY, None)
        piggyback = wire.pop_spans(ddict)
        with self._lock:
            dq = self._inflight[i]
            self._mid_echo[i] = mid is not None
            if mid is None:
                # legacy producer (no correlation echo): REP guarantees
                # per-connection FIFO, so the oldest record matches —
                # sound because the aging pass never re-sends to a
                # KNOWN non-echoing producer (no duplicate replies to
                # shift the matching)
                if any(r["attempt"] > 0 and r.get("reply") is None
                       for r in dq):
                    # ... but a re-send DID go out while echo support was
                    # still unknown (slow first reply): the producer may
                    # have simulated that frame twice, and its duplicate
                    # mid-less reply would land on the wrong record —
                    # attribution is unrecoverable, fail the env cleanly
                    # rather than deliver shifted transitions
                    self.counters.incr("failures")
                    return (
                        f"environment {i} echoes no correlation id but "
                        "was already retried: reply attribution "
                        "unrecoverable"
                    )
                rec = dq[0] if dq else None
            else:
                rec = next((r for r in dq if r["mid"] == mid), None)
            if rec is None or rec.get("reply") is not None:
                self.counters.incr("stale_replies")
                return None
            rec["reply"] = ddict
            if self.spans is not None:
                self.spans.ingest(piggyback)
                self.spans.record(make_span(
                    f"env_{rec['cmd']}", rec["t0_us"], trace=rec["mid"],
                    cat="envpool", args={"env": i},
                ))
            self._states[i].record_success()
            now = time.monotonic()
            wait_s = self._recv_wait_ms() / 1e3
            for r in dq:
                if r is rec:
                    break
                if r.get("reply") is not None:
                    continue
                # older request whose reply was lost: recover it now
                # instead of waiting out the deadline (budget permitting
                # — past it, the aging pass escalates to failure)
                if r["attempt"] >= self.policy.max_retries:
                    continue
                r["attempt"] += 1
                self.counters.incr("retries")
                try:
                    wire.send_message_dealer(
                        self._dealer_socket(i), r["request"],
                        flags=zmq.DONTWAIT,
                    )
                except zmq.Again:
                    continue  # aging pass will deal with it
                r["expires_at"] = (
                    now + wait_s + self._states[i].backoff(r["attempt"])
                )
            while dq and dq[0].get("reply") is not None:
                r = dq.popleft()
                reply = r["reply"]
                self.env_times[i] = reply.get("time")
                if r["discard"]:
                    continue  # post-done frame: consumed, never surfaced
                self._last_obs[i] = reply.pop("obs")
                reply.pop("rgb_array", None)
                if r["cmd"] == "reset":
                    reward, done = 0.0, False
                else:
                    reward = float(reply.pop("reward", 0.0))
                    done = bool(reply.pop("done", False))
                if done:
                    self._needs_reset[i] = True
                    # frames already queued behind the terminal one belong
                    # to the dead episode: consume their replies silently
                    # (they carry post-terminal state and extra dones).
                    # The count rides the terminal transition's info so a
                    # constant-depth driver can top up its resubmission —
                    # without it the env's pipeline shrinks by this many
                    # slots at every episode boundary.
                    dropped = 0
                    for rr in dq:
                        if not rr["discard"]:
                            rr["discard"] = True
                            dropped += 1
                            self.counters.incr("inflight_discards")
                    if dropped:
                        reply["inflight_discarded"] = dropped
                reply["healthy"] = True
                self._ready.append({
                    "env": i, "obs": self._last_obs[i], "reward": reward,
                    "done": done, "info": reply,
                })

    def _synthetic_ready_locked(self, i):
        """One synthetic transition for quarantined env ``i`` (lock
        held): mirrors the lock-step synthetic slot, including the
        exactly-once ``done``."""
        done = i in self._pending_done
        self._pending_done.discard(i)
        self._needs_reset[i] = False
        return {
            "env": i, "obs": self._synthetic_obs(i), "reward": 0.0,
            "done": done, "info": {"healthy": False, "quarantined": True},
        }

    def _assemble_ready(self, entries):
        idx = np.asarray([e["env"] for e in entries], dtype=np.intp)
        if not entries:
            template = next(
                (o for o in self._last_obs if o is not None), None
            )
            return (
                idx,
                _empty_batch_like(template) if template is not None
                else np.empty((0,), np.float32),
                np.empty((0,), np.float32),
                np.empty((0,), bool),
                [],
            )
        return (
            idx,
            collate([e["obs"] for e in entries]),
            np.asarray([e["reward"] for e in entries], np.float32),
            np.asarray([e["done"] for e in entries], bool),
            [e["info"] for e in entries],
        )

    def _synthetic_obs(self, i):
        """Placeholder observation for a quarantined slot: the env's last
        delivered obs, else a zero of any sibling's obs (static batch
        shape either way; live obs are committed to ``_last_obs`` before
        assembly, so a template exists from the very first batch).  The
        bare-0.0 fallback is only reachable when no env has ever
        delivered an observation."""
        if self._last_obs[i] is not None:
            return self._last_obs[i]
        for template in self._last_obs:
            if template is not None:
                return _zero_like(template)
        return 0.0

    def close(self):
        # detach the socket list first (new probes see a closed pool),
        # then wait out any probe mid-flight in its unlocked poll phase —
        # closing a zmq socket under another thread's poll is undefined
        # behavior, and probe phases are bounded by block_ms
        with self._lock:
            socks, self.sockets = self.sockets, []
            dealers, self._dealers = self._dealers, [None] * self.num_envs
            for dq in self._inflight:
                dq.clear()
            self._ready.clear()
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            with self._lock:
                if not any(p and p.get("busy") for p in self._probe):
                    break
            time.sleep(0.01)
        with self._lock:
            for s in socks:
                s.close(0)
            for s in dealers:
                if s is not None:
                    s.close(0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


@contextmanager
def launch_env_pool(
    scene,
    script,
    num_instances,
    background=False,
    timeoutms=DEFAULT_TIMEOUTMS,
    autoreset=True,
    start_port=11000,
    fault_policy=None,
    quarantine=True,
    counters=None,
    pipeline_depth=1,
    trace=False,
    span_recorder=None,
    **kwargs,
):
    """Launch N Blender env instances and yield a connected EnvPool.

    The pool analog of :func:`blendjax.btt.env.launch_env`; extra kwargs
    become CLI flags for every instance's env script.  ``start_port``
    seeds the per-instance address allocation (pick a distinct base when
    several pools may run concurrently on one host).
    """
    from blendjax.btt.launcher import BlenderLauncher

    with BlenderLauncher(
        scene=scene,
        script=script,
        num_instances=num_instances,
        named_sockets=["GYM"],
        instance_args=[list(kwargs_to_cli(kwargs)) for _ in range(num_instances)],
        background=background,
        start_port=start_port,
    ) as bl:
        pool = EnvPool(
            bl.launch_info.addresses["GYM"],
            timeoutms=timeoutms,
            autoreset=autoreset,
            fault_policy=fault_policy,
            quarantine=quarantine,
            counters=counters,
            pipeline_depth=pipeline_depth,
            trace=trace,
            span_recorder=span_recorder,
        )
        try:
            yield pool
        finally:
            pool.close()
