"""Vectorized remote environments — the env-pool abstraction the reference
never had (SURVEY.md §7 "hard parts": batching envs across processes for
vectorized policy training).

``EnvPool`` drives N Blender env instances in lockstep and exposes batched,
numpy-collated ``reset()``/``step(actions)`` whose outputs feed straight
into a jitted policy: stack of obs in, vector of actions out.  RPCs are
pipelined (send to all, then receive from all) so the wall-clock cost per
pool step is one frame of the slowest instance, not the sum.

``step`` auto-resets finished instances by default: an instance reporting
``done`` is sent ``reset`` on the *next* step and contributes its fresh
initial observation (its reward is 0 and done False for that transition) —
the standard vectorized-env contract (cf. gym vector envs), chosen so
policy rollouts under ``jax.jit``/``vmap`` see static shapes.
"""

from __future__ import annotations

from contextlib import contextmanager

import numpy as np
import zmq

from blendjax import wire
from blendjax.btt.collate import collate
from blendjax.btt.constants import DEFAULT_TIMEOUTMS
from blendjax.btt.env import kwargs_to_cli


class EnvPool:
    """Batched client for N remote Blender environments.

    Params
    ------
    addresses: list[str]
        GYM endpoints, one per instance (e.g.
        ``launch_info.addresses['GYM']``).
    timeoutms: int
        Per-socket receive timeout.
    autoreset: bool
        Auto-reset finished instances during ``step``.
    """

    def __init__(self, addresses, timeoutms=DEFAULT_TIMEOUTMS, autoreset=True):
        self._ctx = zmq.Context.instance()
        self.sockets = []
        for addr in addresses:
            s = self._ctx.socket(zmq.REQ)
            s.setsockopt(zmq.LINGER, 0)
            s.setsockopt(zmq.SNDTIMEO, timeoutms * 10)
            s.setsockopt(zmq.RCVTIMEO, timeoutms)
            s.setsockopt(zmq.REQ_RELAXED, 1)
            s.setsockopt(zmq.REQ_CORRELATE, 1)
            s.connect(addr)
            self.sockets.append(s)
        self.num_envs = len(addresses)
        self.env_times = [None] * self.num_envs
        self._needs_reset = np.ones(self.num_envs, dtype=bool)
        self.autoreset = autoreset

    # -- pipelined RPC ------------------------------------------------------

    def _exchange(self, requests):
        """Send one request per env, then collect all replies (pipelined)."""
        for sock, req in zip(self.sockets, requests):
            try:
                wire.send_message(sock, req)
            except zmq.Again:
                raise TimeoutError("Failed to send to remote environment") from None
        replies = []
        for i, sock in enumerate(self.sockets):
            try:
                ddict = wire.recv_message(sock)
            except zmq.Again:
                raise TimeoutError(
                    f"No response from environment {i} within timeout"
                ) from None
            self.env_times[i] = ddict.get("time")
            replies.append(ddict)
        return replies

    def reset(self):
        """Reset all instances; returns ``(batched_obs, infos)``."""
        replies = self._exchange(
            [{"cmd": "reset", "time": t} for t in self.env_times]
        )
        self._needs_reset[:] = False
        obs = [r.pop("obs") for r in replies]
        for r in replies:
            r.pop("rgb_array", None)
        return collate(obs), replies

    def step(self, actions):
        """Step all instances with a length-N batch of actions.

        Returns ``(obs, rewards, dones, infos)`` with obs collated and
        rewards/dones as float32/bool arrays.  With ``autoreset``,
        instances that reported done on the previous step are reset now.
        """
        if len(actions) != self.num_envs:
            raise ValueError(f"expected {self.num_envs} actions, got {len(actions)}")
        requests = []
        for i, action in enumerate(actions):
            if self.autoreset and self._needs_reset[i]:
                requests.append({"cmd": "reset", "time": self.env_times[i]})
            else:
                requests.append(
                    {"cmd": "step", "action": action, "time": self.env_times[i]}
                )
        replies = self._exchange(requests)

        obs, rewards, dones = [], [], []
        for i, r in enumerate(replies):
            was_reset = self.autoreset and self._needs_reset[i]
            obs.append(r.pop("obs"))
            rewards.append(0.0 if was_reset else float(r.pop("reward", 0.0)))
            done = False if was_reset else bool(r.pop("done", False))
            dones.append(done)
            self._needs_reset[i] = done
            r.pop("rgb_array", None)
        return (
            collate(obs),
            np.asarray(rewards, np.float32),
            np.asarray(dones, bool),
            replies,
        )

    def close(self):
        for s in self.sockets:
            s.close(0)
        self.sockets = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


@contextmanager
def launch_env_pool(
    scene,
    script,
    num_instances,
    background=False,
    timeoutms=DEFAULT_TIMEOUTMS,
    autoreset=True,
    start_port=11000,
    **kwargs,
):
    """Launch N Blender env instances and yield a connected EnvPool.

    The pool analog of :func:`blendjax.btt.env.launch_env`; extra kwargs
    become CLI flags for every instance's env script.  ``start_port``
    seeds the per-instance address allocation (pick a distinct base when
    several pools may run concurrently on one host).
    """
    from blendjax.btt.launcher import BlenderLauncher

    with BlenderLauncher(
        scene=scene,
        script=script,
        num_instances=num_instances,
        named_sockets=["GYM"],
        instance_args=[list(kwargs_to_cli(kwargs)) for _ in range(num_instances)],
        background=background,
        start_port=start_port,
    ) as bl:
        pool = EnvPool(
            bl.launch_info.addresses["GYM"],
            timeoutms=timeoutms,
            autoreset=autoreset,
        )
        try:
            yield pool
        finally:
            pool.close()
