"""Vectorized remote environments — the env-pool abstraction the reference
never had (SURVEY.md §7 "hard parts": batching envs across processes for
vectorized policy training).

``EnvPool`` drives N Blender env instances in lockstep and exposes batched,
numpy-collated ``reset()``/``step(actions)`` whose outputs feed straight
into a jitted policy: stack of obs in, vector of actions out.  RPCs are
pipelined (send to all, then receive from all) so the wall-clock cost per
pool step is one frame of the slowest instance, not the sum.

``step`` auto-resets finished instances by default: an instance reporting
``done`` is sent ``reset`` on the *next* step and contributes its fresh
initial observation (its reward is 0 and done False for that transition) —
the standard vectorized-env contract (cf. gym vector envs), chosen so
policy rollouts under ``jax.jit``/``vmap`` see static shapes.

Fault tolerance (see docs/fault_tolerance.md): exchanges run under a
:class:`blendjax.btt.faults.FaultPolicy` (retries with backoff, per-call
deadline, per-env circuit breaker).  With ``quarantine=True`` (default) an
env that exhausts its retries is *quarantined* instead of failing the
whole batched step: it stops receiving RPCs, contributes a synthetic
transition (last known observation, zero reward, ``done=True`` exactly
once so trainers close the episode), and is flagged in the ``healthy``
mask / per-env infos.  Training continues on the N-1 live envs.
Quarantined envs are probed in the background of each ``step`` (or by a
:class:`blendjax.btt.supervise.FleetSupervisor`) with a fresh socket and a
``reset`` resync handshake; on success the env re-enters the pool through
the standard autoreset contract (fresh initial obs, zero reward).  Only
when *every* env is quarantined does ``step`` raise.
"""

from __future__ import annotations

import logging
import threading
import time
from contextlib import contextmanager

import numpy as np
import zmq

from blendjax import wire
from blendjax.btt.collate import collate
from blendjax.btt.constants import DEFAULT_TIMEOUTMS
from blendjax.btt.env import kwargs_to_cli
from blendjax.btt.faults import FaultPolicy
from blendjax.utils.timing import fleet_counters

logger = logging.getLogger("blendjax")


def _zero_like(obs):
    """Type/shape-preserving zero observation for a quarantined env that
    never delivered one (keeps batch collation static-shaped)."""
    if isinstance(obs, np.ndarray):
        return np.zeros_like(obs)
    if isinstance(obs, dict):
        return {k: _zero_like(v) for k, v in obs.items()}
    if isinstance(obs, (list, tuple)):
        seq = [_zero_like(v) for v in obs]
        return seq if isinstance(obs, list) else tuple(seq)
    if isinstance(obs, bool):
        return False
    if isinstance(obs, (int, float, complex, np.number)):
        return type(obs)(0)
    return obs


class EnvPool:
    """Batched client for N remote Blender environments.

    Params
    ------
    addresses: list[str]
        GYM endpoints, one per instance (e.g.
        ``launch_info.addresses['GYM']``).
    timeoutms: int
        Per-socket receive timeout (per-attempt wait when the fault
        policy sets no ``deadline_s``).
    autoreset: bool
        Auto-reset finished instances during ``step``.
    fault_policy: FaultPolicy | None
        Retry/backoff/circuit policy for exchanges and re-admission
        probes; None installs the default :class:`FaultPolicy`.  Pass
        ``FaultPolicy(max_retries=0)`` for strict single-attempt
        semantics (retrying ``step`` against a slow-but-alive env can
        advance it an extra frame — see :mod:`blendjax.btt.faults`).
    quarantine: bool
        Degraded mode: isolate failing envs and keep stepping the rest
        (see module docstring).  False restores fail-whole-batch:
        any env exhausting its retries raises ``TimeoutError`` naming it
        (successful siblings' ``env_times`` are committed first, so a
        partial exchange never desyncs the survivors).
    counters: EventCounters | None
        Fault-event sink; defaults to the process-wide
        ``blendjax.utils.timing.fleet_counters``.
    """

    def __init__(
        self,
        addresses,
        timeoutms=DEFAULT_TIMEOUTMS,
        autoreset=True,
        fault_policy=None,
        quarantine=True,
        counters=None,
    ):
        self._ctx = zmq.Context.instance()
        self._addresses = list(addresses)
        self._timeoutms = timeoutms
        self.sockets = [self._connect(a) for a in self._addresses]
        self.num_envs = len(self._addresses)
        self.env_times = [None] * self.num_envs
        self._needs_reset = np.ones(self.num_envs, dtype=bool)
        self.autoreset = autoreset
        self.quarantine = quarantine
        self.policy = fault_policy if fault_policy is not None else FaultPolicy()
        self.counters = counters if counters is not None else fleet_counters
        # quarantine state; _lock guards every transition (step runs on the
        # training thread, probes may run from a supervisor thread)
        self._lock = threading.RLock()
        self._exchanging = set()  # envs whose sockets a step/reset is using
        self._quarantined = np.zeros(self.num_envs, dtype=bool)
        self._states = [self.policy.new_state(i) for i in range(self.num_envs)]
        self._probe = [None] * self.num_envs  # per-env re-admission attempt
        self._fresh = [None] * self.num_envs  # unconsumed resync reset reply
        self._pending_done = set()  # envs owing their one quarantine done=True
        self._last_obs = [None] * self.num_envs

    def _connect(self, addr):
        s = self._ctx.socket(zmq.REQ)
        s.setsockopt(zmq.LINGER, 0)
        s.setsockopt(zmq.SNDTIMEO, self._timeoutms * 10)
        s.setsockopt(zmq.RCVTIMEO, self._timeoutms)
        s.setsockopt(zmq.REQ_RELAXED, 1)
        s.setsockopt(zmq.REQ_CORRELATE, 1)
        s.connect(addr)
        return s

    # -- health surface -----------------------------------------------------

    @property
    def healthy(self):
        """Boolean mask, True for envs currently serving real transitions."""
        with self._lock:
            return ~self._quarantined.copy()

    @property
    def quarantined(self):
        with self._lock:
            return self._quarantined.copy()

    # -- pipelined RPC ------------------------------------------------------

    def _recv_wait_ms(self):
        """Per-attempt recv wait: the policy deadline when set (so one
        slow env cannot eat the whole socket timeout per attempt), else
        the socket timeout."""
        if self.policy.deadline_s is not None:
            return max(1, int(self.policy.deadline_s * 1000))
        return self._timeoutms

    def _exchange(self, requests, indices=None):
        """Pipelined exchange over env ``indices`` (default: all).

        Sends every request, then collects replies; an env that fails its
        send or exhausts its recv retries lands in ``failed`` instead of
        aborting the exchange, and every *successful* reply commits its
        ``env_times`` entry regardless of sibling failures (a partial
        exchange must never desync the survivors).

        Returns ``(replies, failed)``: ``replies`` maps env index to its
        reply dict, ``failed`` maps env index to the error string.
        """
        if indices is None:
            indices = list(range(self.num_envs))
        # socket mutual exclusion with the probe machinery, both ways: an
        # env quarantined between the caller's snapshot and this point may
        # have a probe mid-flight on its (re-dialed) socket, and a probe
        # must never touch a socket this exchange is using.  Quarantined /
        # busy-probed envs are failed up front without an RPC.
        with self._lock:
            blocked = {
                i for i in indices
                if self._quarantined[i]
                or (self._probe[i] is not None and self._probe[i].get("busy"))
            }
            self._exchanging = set(indices) - blocked
        try:
            return self._exchange_locked_out(requests, indices, blocked)
        finally:
            with self._lock:
                self._exchanging = set()

    def _exchange_locked_out(self, requests, indices, blocked=()):
        reqs = dict(zip(indices, requests))
        replies, failed = {}, {}
        awaiting = []
        for i in indices:
            if i in blocked:
                failed[i] = f"environment {i} is quarantined"
                continue
            if self._states[i].circuit_open():
                # the breaker protects strict-mode pools too: a dead env
                # stops costing (max_retries+1) recv waits per step
                self.counters.incr("circuit_rejections")
                failed[i] = (
                    f"environment {i} circuit open after "
                    f"{self._states[i].consecutive_failures} consecutive "
                    "failures"
                )
                continue
            try:
                wire.send_message(self.sockets[i], reqs[i])
                awaiting.append(i)
            except zmq.Again:
                self.counters.incr("timeouts")
                self._states[i].record_failure(self.counters)
                failed[i] = f"send to environment {i} timed out"
        # recv phase: one poller over every awaiting socket, in rounds —
        # attempt r waits at most one recv budget for ALL still-pending
        # envs together, so K simultaneously dead envs stall a step for
        # ~(max_retries+1) recv waits total, not K times that
        wait_ms = self._recv_wait_ms()
        pending = set(awaiting)
        poller = zmq.Poller()
        for i in pending:
            poller.register(self.sockets[i], zmq.POLLIN)
        for attempt in range(self.policy.max_retries + 1):
            deadline = time.monotonic() + wait_ms / 1e3
            while pending:
                remaining_ms = int((deadline - time.monotonic()) * 1000)
                if remaining_ms <= 0:
                    break
                events = dict(poller.poll(remaining_ms))
                if not events:
                    break
                for i in list(pending):
                    sock = self.sockets[i]
                    if not (events.get(sock, 0) & zmq.POLLIN):
                        continue
                    try:
                        ddict = wire.recv_message(sock)
                    except Exception:
                        # a garbled/unpicklable reply is an env fault,
                        # not a pool crash: discard it and let the retry
                        # / quarantine machinery handle the env
                        logger.warning(
                            "env %d: malformed reply discarded", i,
                            exc_info=True,
                        )
                        continue
                    self.env_times[i] = ddict.get("time")
                    self._states[i].record_success()
                    replies[i] = ddict
                    poller.unregister(sock)
                    pending.discard(i)
            if not pending:
                break
            for i in pending:
                self.counters.incr("timeouts")
                self._states[i].record_failure(self.counters)
            if attempt >= self.policy.max_retries:
                for i in pending:
                    self.counters.incr("failures")
                    failed[i] = (
                        f"no response from environment {i} within timeout"
                    )
                break
            # one shared backoff per round (the slowest of the pending
            # envs' jittered delays), then re-send to all of them —
            # REQ_RELAXED allows it, REQ_CORRELATE drops the stale reply
            self.counters.incr("retries", len(pending))
            delay = max(
                self._states[i].backoff(attempt + 1) for i in pending
            )
            if delay > 0:
                time.sleep(delay)
            for i in list(pending):
                try:
                    wire.send_message(self.sockets[i], reqs[i])
                except zmq.Again:
                    self.counters.incr("failures")
                    failed[i] = f"send to environment {i} timed out"
                    poller.unregister(self.sockets[i])
                    pending.discard(i)
        return replies, failed

    def _fail_or_quarantine(self, failed):
        """Route exchange failures: quarantine mode isolates each failed
        env; strict mode raises (after the successes were committed)."""
        if not failed:
            return
        if not self.quarantine:
            raise TimeoutError("; ".join(failed.values()))
        for i, reason in failed.items():
            self.quarantine_env(i, reason=reason)

    # -- quarantine & re-admission ------------------------------------------

    def quarantine_env(self, i, reason="unresponsive"):
        """Isolate env ``i``: no more RPCs until a probe re-admits it.
        Idempotent; safe from any thread (the supervisor calls this
        proactively on producer death, ahead of any timeout)."""
        with self._lock:
            if self._quarantined[i]:
                return
            self._quarantined[i] = True
            self._pending_done.add(i)
            self._fresh[i] = None
            self._probe[i] = {"active": False, "sent": False, "started": 0.0,
                              "attempts": 0, "next_at": 0.0}
            self.counters.incr("quarantines")
        logger.warning("env %d quarantined: %s", i, reason)

    def notify_respawn(self, i):
        """The producer behind env ``i`` was restarted: drop the backoff
        and circuit state so the next probe runs immediately on a fresh
        socket (called by :class:`~blendjax.btt.supervise.FleetSupervisor`
        after a watchdog respawn)."""
        with self._lock:
            if not self._quarantined[i]:
                return
            self._states[i] = self.policy.new_state(i)
            p = self._probe[i]
            if p is not None and p.get("busy"):
                # a probe is mid-flight on this env's socket from another
                # thread: don't replace its attempt record (a fresh one
                # would let a second probe redial — and close — the
                # socket in use); just clear the backoff so the next
                # attempt after it resolves runs immediately
                p.update(next_at=0.0, attempts=0)
            else:
                self._probe[i] = {"active": False, "sent": False,
                                  "started": 0.0, "attempts": 0,
                                  "next_at": 0.0}

    def probe(self, block_ms=0):
        """Attempt re-admission of quarantined envs (backoff/circuit
        gated).  Each attempt is a three-phase async handshake spread over
        successive calls — dial a fresh socket, send a ``reset`` resync
        once the connection is writable, collect the fresh initial
        observation — so ``block_ms=0`` (the in-``step`` mode) never
        blocks the training loop; positive ``block_ms`` bounds each wait
        (supervisor heal loop).  An attempt that exceeds the policy
        deadline fails, feeds the circuit breaker, and backs off.
        Returns the list of env indices re-admitted by this call."""
        readmitted = []
        deadline_s = (
            self.policy.deadline_s
            if self.policy.deadline_s is not None
            else self._timeoutms / 1e3
        )
        # phase 1 (locked, non-blocking): pick due probes, dial fresh
        # sockets, and mark each one busy so concurrent probe callers
        # (training step vs supervisor heal thread) never share a socket
        work = []
        with self._lock:
            if not self.sockets:
                return readmitted  # pool closed (a heal tick may race it)
            now = time.monotonic()
            for i in np.flatnonzero(self._quarantined):
                i = int(i)
                st, p = self._states[i], self._probe[i]
                if p is None or p.get("busy") or i in self._exchanging:
                    continue
                if st.circuit_open(now) or now < p["next_at"]:
                    continue
                if not p.get("active"):
                    # reconnect: a fresh REQ drops any half-done request
                    # cycle and re-dials the (possibly re-bound) endpoint
                    self.sockets[i].close(0)
                    self.sockets[i] = self._connect(self._addresses[i])
                    p.update(active=True, sent=False, started=now)
                p["busy"] = True
                work.append((i, self.sockets[i], p))
        # phase 2 (unlocked): the blocking polls — a dead endpoint must
        # not starve step()/reset() of the pool lock while we wait on it
        for i, sock, p in work:
            reply, malformed = None, False
            try:
                if not p["sent"] and sock.poll(block_ms, zmq.POLLOUT):
                    try:
                        wire.send_message(
                            sock, {"cmd": "reset", "time": None},
                            flags=zmq.NOBLOCK,
                        )
                        p["sent"] = True
                    except zmq.Again:
                        pass  # connection raced away; retry within deadline
                if p["sent"] and sock.poll(block_ms, zmq.POLLIN):
                    try:
                        reply = wire.recv_message(sock)
                    except Exception:
                        malformed = True
                        logger.warning(
                            "env %d: malformed resync reply discarded", i,
                            exc_info=True,
                        )
            finally:
                # phase 3 (locked): apply the outcome
                with self._lock:
                    p["busy"] = False
                    if reply is not None and self._quarantined[i]:
                        self.env_times[i] = reply.get("time")
                        self._fresh[i] = reply
                        self._quarantined[i] = False
                        self._needs_reset[i] = False
                        self._probe[i] = None
                        # an unsurfaced quarantine done stays pending:
                        # step() emits the interrupted episode's terminal
                        # transition before consuming the resync obs
                        self._states[i].record_success()
                        self.counters.incr("readmissions")
                        readmitted.append(i)
                        logger.warning("env %d re-admitted after resync", i)
                    elif malformed or (
                        time.monotonic() - p["started"] >= deadline_s
                    ):
                        self.counters.incr("timeouts")
                        self._probe_failed(i, time.monotonic())
        return readmitted

    def _probe_failed(self, i, now):
        """One re-admission attempt failed: back off (policy jitter) and
        schedule a fresh-socket retry; consecutive failures feed the
        circuit breaker so a permanently-dead endpoint stops being dialed
        every step."""
        p = self._probe[i]
        p["attempts"] += 1
        p["active"] = False
        self._states[i].record_failure(self.counters)
        p["next_at"] = now + self._states[i].backoff(p["attempts"])

    # -- batched API --------------------------------------------------------

    def reset(self):
        """Reset all live instances; returns ``(batched_obs, infos)``.

        Quarantined envs contribute their last known (or zero) observation
        with ``info['healthy'] = False``; they rejoin via the re-admission
        handshake, which itself performs a ``reset``.  Raises when every
        env is quarantined.
        """
        self.probe(block_ms=0)
        with self._lock:
            self._fresh = [None] * self.num_envs  # superseded by this reset
            live = [i for i in range(self.num_envs) if not self._quarantined[i]]
        if not live:
            raise TimeoutError("all environments are quarantined")
        if not self.quarantine and len(live) < self.num_envs:
            # strict mode: a supervisor-quarantined env fails the call
            # instead of contributing a synthetic slot
            raise TimeoutError(
                "environment(s) "
                f"{[i for i in range(self.num_envs) if i not in live]} are "
                "quarantined (strict mode: no degraded batches)"
            )
        replies, failed = self._exchange(
            [{"cmd": "reset", "time": self.env_times[i]} for i in live],
            indices=live,
        )
        self._fail_or_quarantine(failed)
        if not replies:
            # the exchange in which the LAST live envs fail must raise,
            # not return an all-synthetic batch (which, before any env
            # ever delivered an obs, couldn't even be shaped correctly)
            raise TimeoutError(
                "all environments are quarantined: "
                + "; ".join(failed.values())
            )
        # commit every live obs BEFORE assembly so a quarantined slot can
        # synthesize a shape-matched placeholder even on the first batch
        for j, r in replies.items():
            self._last_obs[j] = r.pop("obs")
        obs, infos = [], []
        for i in range(self.num_envs):
            r = replies.get(i)
            if r is not None:
                self._needs_reset[i] = False
                # an explicit reset IS the episode boundary; any owed
                # quarantine done for this env is thereby delivered
                self._pending_done.discard(i)
                r.pop("rgb_array", None)
                r["healthy"] = True
                obs.append(self._last_obs[i])
            else:
                obs.append(self._synthetic_obs(i))
                r = {"healthy": False, "quarantined": True}
            infos.append(r)
        return collate(obs), infos

    def step(self, actions):
        """Step all instances with a length-N batch of actions.

        Returns ``(obs, rewards, dones, infos)`` with obs collated and
        rewards/dones as float32/bool arrays.  With ``autoreset``,
        instances that reported done on the previous step are reset now.

        Under quarantine, isolated envs return synthetic transitions
        (``info['healthy'] = False``) and freshly re-admitted envs return
        their resync observation through the autoreset contract
        (``info['readmitted'] = True``, zero reward).
        """
        if len(actions) != self.num_envs:
            raise ValueError(f"expected {self.num_envs} actions, got {len(actions)}")
        self.probe(block_ms=0)
        with self._lock:
            quarantined = self._quarantined.copy()
            fresh, owe_done = {}, set()
            for i in range(self.num_envs):
                if self._fresh[i] is not None and not quarantined[i]:
                    if i in self._pending_done:
                        # re-admission won the race with the training
                        # loop: the interrupted episode's terminal
                        # transition (done=True on the last real obs) must
                        # still surface exactly once — emit it THIS step
                        # and hold the fresh resync obs for the next one
                        self._pending_done.discard(i)
                        owe_done.add(i)
                    else:
                        fresh[i] = self._fresh[i]
                        self._fresh[i] = None
        if quarantined.all():
            raise TimeoutError("all environments are quarantined")
        if not self.quarantine and quarantined.any():
            # strict mode never serves synthetic transitions — a
            # supervisor (or caller) may still quarantine_env() on
            # producer death, and the strict caller opted to fail instead
            # of training on fabricated data
            raise TimeoutError(
                "environment(s) "
                f"{[int(i) for i in np.flatnonzero(quarantined)]} are "
                "quarantined (strict mode: no degraded batches)"
            )
        send_idx, requests = [], []
        for i, action in enumerate(actions):
            if quarantined[i] or i in fresh or i in owe_done:
                continue
            send_idx.append(i)
            if self.autoreset and self._needs_reset[i]:
                requests.append({"cmd": "reset", "time": self.env_times[i]})
            else:
                requests.append(
                    {"cmd": "step", "action": action, "time": self.env_times[i]}
                )
        replies, failed = self._exchange(requests, indices=send_idx)
        self._fail_or_quarantine(failed)
        if not replies and not fresh and not owe_done:
            # every remaining live env failed in THIS call: raise rather
            # than hand back a batch with no real transition in it
            raise TimeoutError(
                "all environments are quarantined: "
                + "; ".join(failed.values())
            )
        with self._lock:
            quarantined = self._quarantined.copy()
            # an env owes its one quarantine done=True only while it is
            # actually served synthetically: a reply that raced the
            # quarantine keeps its real transition, and a slot being served
            # from `fresh`/`owe_done` this step emits its own bookkeeping —
            # in every excluded case the pending done survives and fires on
            # that env's next synthetic step instead of vanishing
            q_done = {
                i for i in self._pending_done
                if quarantined[i]
                and i not in replies
                and i not in fresh
                and i not in owe_done
            }
            self._pending_done -= q_done

        # commit every live obs BEFORE assembly so a quarantined slot can
        # synthesize a shape-matched placeholder even on the first batch
        for j, r in replies.items():
            self._last_obs[j] = r.pop("obs")
        for j, f in fresh.items():
            self._last_obs[j] = f.pop("obs")
        obs, rewards, dones, infos = [], [], [], []
        for i in range(self.num_envs):
            r = replies.get(i)
            if i in fresh:
                f = fresh[i]
                f.pop("rgb_array", None)
                f.update(healthy=True, readmitted=True)
                obs.append(self._last_obs[i])
                rewards.append(0.0)
                dones.append(False)
                self._needs_reset[i] = False
                infos.append(f)
            elif r is not None:
                was_reset = self.autoreset and self._needs_reset[i]
                obs.append(self._last_obs[i])
                rewards.append(0.0 if was_reset else float(r.pop("reward", 0.0)))
                done = False if was_reset else bool(r.pop("done", False))
                dones.append(done)
                self._needs_reset[i] = done
                r.pop("rgb_array", None)
                r["healthy"] = True
                infos.append(r)
            elif i in owe_done:
                # terminal close-out of the interrupted episode: last real
                # obs, done=True; the env is healthy again and its held
                # resync obs arrives next step via the fresh branch
                obs.append(self._synthetic_obs(i))
                rewards.append(0.0)
                dones.append(True)
                self._needs_reset[i] = False
                infos.append(
                    {"healthy": True, "quarantined": True, "interrupted": True}
                )
            else:
                obs.append(self._synthetic_obs(i))
                rewards.append(0.0)
                dones.append(i in q_done)
                self._needs_reset[i] = False
                infos.append({"healthy": False, "quarantined": True})
        return (
            collate(obs),
            np.asarray(rewards, np.float32),
            np.asarray(dones, bool),
            infos,
        )

    def _synthetic_obs(self, i):
        """Placeholder observation for a quarantined slot: the env's last
        delivered obs, else a zero of any sibling's obs (static batch
        shape either way; live obs are committed to ``_last_obs`` before
        assembly, so a template exists from the very first batch).  The
        bare-0.0 fallback is only reachable when no env has ever
        delivered an observation."""
        if self._last_obs[i] is not None:
            return self._last_obs[i]
        for template in self._last_obs:
            if template is not None:
                return _zero_like(template)
        return 0.0

    def close(self):
        # detach the socket list first (new probes see a closed pool),
        # then wait out any probe mid-flight in its unlocked poll phase —
        # closing a zmq socket under another thread's poll is undefined
        # behavior, and probe phases are bounded by block_ms
        with self._lock:
            socks, self.sockets = self.sockets, []
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            with self._lock:
                if not any(p and p.get("busy") for p in self._probe):
                    break
            time.sleep(0.01)
        with self._lock:
            for s in socks:
                s.close(0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


@contextmanager
def launch_env_pool(
    scene,
    script,
    num_instances,
    background=False,
    timeoutms=DEFAULT_TIMEOUTMS,
    autoreset=True,
    start_port=11000,
    fault_policy=None,
    quarantine=True,
    counters=None,
    **kwargs,
):
    """Launch N Blender env instances and yield a connected EnvPool.

    The pool analog of :func:`blendjax.btt.env.launch_env`; extra kwargs
    become CLI flags for every instance's env script.  ``start_port``
    seeds the per-instance address allocation (pick a distinct base when
    several pools may run concurrently on one host).
    """
    from blendjax.btt.launcher import BlenderLauncher

    with BlenderLauncher(
        scene=scene,
        script=script,
        num_instances=num_instances,
        named_sockets=["GYM"],
        instance_args=[list(kwargs_to_cli(kwargs)) for _ in range(num_instances)],
        background=background,
        start_port=start_port,
    ) as bl:
        pool = EnvPool(
            bl.launch_info.addresses["GYM"],
            timeoutms=timeoutms,
            autoreset=autoreset,
            fault_policy=fault_policy,
            quarantine=quarantine,
            counters=counters,
        )
        try:
            yield pool
        finally:
            pool.close()
