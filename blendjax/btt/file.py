"""Single-file message log for record/replay (reference ``btt/file.py:10-132``).

Format (unchanged from the reference so ``.btr`` files interoperate both
ways): a pickled int64 offsets array of fixed capacity ``max_messages`` as
header, rewritten in place on close, followed by one pickled message dict
per record.  The fixed-capacity header has a stable byte length, which is
what makes the in-place rewrite sound.

This is the framework's checkpoint/resume analog (SURVEY.md §5): the raw
stream is persisted so training can replay deterministically without any
Blender process, and map-style access makes shuffling possible.
"""

from __future__ import annotations

import io
import logging
import pickle
from pathlib import Path

import numpy as np

from blendjax import wire

logger = logging.getLogger("blendjax")


#: Default write-buffer size.  The reference opens with ``buffering=0``
#: — one syscall per ``write`` — which costs a measurable fraction of
#: the record path at high message rates (small messages are worst:
#: header + payload = 2+ syscalls each; see ``make replaybench``'s
#: ``record_buffered_x`` for the measured before/after).  Buffered
#: writes change nothing about the format: ``tell()`` on a
#: ``BufferedWriter`` reports the logical position, and the close path
#: flushes explicitly before the in-place header rewrite.
DEFAULT_WRITE_BUFFER = 1 << 20


class FileRecorder:
    """Context manager appending raw messages to an offset-indexed log.

    Params
    ------
    outpath: str | Path
        File to write.
    max_messages: int
        Capacity; further ``save`` calls are dropped (matching reference
        semantics, ``file.py:46``) — with a once-per-recorder warning,
        a ``dropped`` count, and a ``record_drops`` event so the loss
        is visible (the reference drops silently).
    buffering: int
        Passed to ``io.open``; 0 restores the reference's unbuffered
        one-syscall-per-record behavior (kept for the before/after
        benchmark comparison).
    counters: EventCounters | None
        Sink for ``record_drops``; defaults to the process-wide
        ``blendjax.utils.timing.fleet_counters`` so
        ``FleetSupervisor.health()`` surfaces truncated recordings.
    """

    def __init__(self, outpath="blendjax.btr", max_messages=100000,
                 buffering=DEFAULT_WRITE_BUFFER, counters=None):
        from blendjax.utils.timing import fleet_counters

        outpath = Path(outpath)
        outpath.parent.mkdir(parents=True, exist_ok=True)
        self.outpath = outpath
        self.capacity = max_messages
        self.buffering = buffering
        self.file = None
        self.dropped = 0
        self.counters = counters if counters is not None else fleet_counters
        logger.info("Recording to %s, capacity %d messages.", outpath, max_messages)

    def __enter__(self):
        self.file = io.open(self.outpath, "wb", buffering=self.buffering)
        self.offsets = np.full(self.capacity, -1, dtype=np.int64)
        self.num_messages = 0
        self.dropped = 0
        self._write_header()
        return self

    def _write_header(self):
        self.file.write(pickle.dumps(self.offsets, protocol=wire.PICKLE_PROTOCOL))

    def save(self, data, is_pickled=False):
        """Append one message (dict, or already-pickled bytes).

        Returns True when stored; False once ``capacity`` is reached —
        the message is dropped (recording truncated, warned once per
        recorder, counted in ``dropped`` / the ``record_drops`` event).
        """
        if self.num_messages >= self.capacity:
            if self.dropped == 0:
                logger.warning(
                    "FileRecorder %s is full (%d messages): further "
                    "messages are DROPPED — the recording is truncated, "
                    "raise max_messages to keep them.",
                    self.outpath, self.capacity,
                )
            self.dropped += 1
            self.counters.incr("record_drops")
            return False
        self.offsets[self.num_messages] = self.file.tell()
        self.num_messages += 1
        if is_pickled:
            self.file.write(data)
        else:
            self.file.write(pickle.dumps(data, protocol=wire.PICKLE_PROTOCOL))
        return True

    def flush(self):
        """Push buffered records to the OS now.  The replay shard service
        calls this before acknowledging an ``append`` RPC, so every row
        the client has an ack for is recoverable from the spill log even
        if the shard is SIGKILLed the next instant (crash-exact recovery:
        see :func:`scan_messages` for how an unfinalized log is read
        back)."""
        if self.file is not None:
            self.file.flush()

    def save_frames(self, frames):
        """Append a message captured as raw ZMQ frames.

        Single-frame (compat encoding) messages hit disk verbatim; multipart
        raw-buffer messages are decoded and re-pickled so the on-disk format
        stays reference-compatible regardless of the wire encoding.
        """
        if len(frames) == 1:
            self.save(bytes(frames[0]), is_pickled=True)
        else:
            self.save(wire.decode_raw_frames(frames), is_pickled=False)

    def __exit__(self, *args):
        # flush buffered records BEFORE the in-place header rewrite:
        # BufferedWriter.seek would flush implicitly, but the invariant
        # (every record byte lands before any header byte is replaced)
        # is load-bearing for crash forensics, so it is explicit
        self.file.flush()
        self.file.seek(0)
        self._write_header()  # fixed byte length: same capacity, same protocol
        self.file.close()
        self.file = None
        return False

    @staticmethod
    def filename(prefix, worker_idx):
        """Per-worker file name ``{prefix}_{worker:02d}.btr``."""
        return f"{prefix}_{worker_idx:02d}.btr"


def scan_messages(path):
    """Yield messages from a ``.btr`` file **sequentially, ignoring the
    offsets header** — the crash-recovery read path.

    :class:`FileRecorder` rewrites its header only on clean close; a
    recorder killed mid-stream leaves the header all ``-1``, which
    :class:`FileReader` (correctly, for its random-access contract)
    reads as an empty file.  Records are nonetheless laid out back to
    back after the header, so this scanner recovers every fully-written
    one: it unpickles the header to find where records start, then
    unpickles records until EOF.  A torn final record (the crash landed
    mid-``write``) ends the scan cleanly — everything before it was
    flushed and is returned intact.
    """
    with io.open(path, "rb") as f:
        try:
            pickle.load(f)  # the (possibly unfinalized) offsets header
        except (EOFError, pickle.UnpicklingError):
            return
        while True:
            try:
                yield pickle.load(f)
            except (EOFError, pickle.UnpicklingError, AttributeError,
                    MemoryError, ValueError):
                # torn tail: the crash interrupted the last write
                return


class FileReader:
    """Random access to messages written by :class:`FileRecorder`.

    The file handle is opened lazily per process so readers cross
    worker-process boundaries safely (reference ``file.py:100-108``).
    """

    def __init__(self, path):
        self.path = path
        self.offsets = FileReader.read_offsets(path)
        self._file = None

    def __len__(self):
        return len(self.offsets)

    def __getitem__(self, idx):
        if self._file is None:
            self._file = io.open(self.path, "rb")
        self._file.seek(self.offsets[idx])
        return pickle.load(self._file)

    def close(self):
        if self._file is not None:
            self._file.close()
            self._file = None

    @staticmethod
    def read_offsets(fname):
        """Load the header; trims unused (-1) capacity entries."""
        if not Path(fname).exists():
            raise FileNotFoundError(f"Cannot open {fname} for reading.")
        with io.open(fname, "rb") as f:
            offsets = pickle.load(f)
        unused = np.flatnonzero(offsets == -1)
        count = unused[0] if len(unused) else len(offsets)
        return offsets[:count]
