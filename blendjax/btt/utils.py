"""Consumer-side network utilities (reference ``btt/utils.py:2-17``)."""

from __future__ import annotations

import socket


def get_primary_ip() -> str:
    """IP of the default-route interface; falls back to localhost.

    Uses the UDP-connect trick: no packet is sent, the OS just resolves the
    route.  Used by the launcher's ``bind_addr='primaryip'`` mode so remote
    consumers on other TPU-VM hosts can connect (reference
    ``launcher.py:187-188``).
    """
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()
