"""``blendjax-launch`` — headless multi-machine launch CLI
(reference ``btt/apps/launch.py:26-41``).

Reads a JSON file whose dict matches :class:`BlenderLauncher` kwargs,
launches the fleet, writes connection info to ``--out-launch-info``
(default ``launch_info.json``), and blocks until the instances exit.  A
consumer on another host restores the addresses with
``LaunchInfo.load_json`` and connects its dataset/duplex sockets directly.

Example JSON::

    {
        "scene": "",
        "script": "cube.blend.py",
        "num_instances": 4,
        "named_sockets": ["DATA"],
        "background": true,
        "bind_addr": "primaryip",
        "seed": 10
    }
"""

from __future__ import annotations

import argparse
import json
import logging

from blendjax.btt.launch_info import LaunchInfo
from blendjax.btt.launcher import BlenderLauncher


def main(inargs=None):
    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser(
        "blendjax-launch",
        description=__doc__,
        formatter_class=argparse.RawTextHelpFormatter,
    )
    parser.add_argument(
        "--out-launch-info",
        default="launch_info.json",
        help="Path to write connection info to.",
    )
    parser.add_argument(
        "jsonargs", help="Path to JSON dict of BlenderLauncher kwargs."
    )
    args = parser.parse_args(inargs)

    with open(args.jsonargs, "r", encoding="utf-8") as fp:
        launch_args = json.load(fp)

    with BlenderLauncher(**launch_args) as bl:
        LaunchInfo.save_json(args.out_launch_info, bl.launch_info)
        bl.wait()


if __name__ == "__main__":
    main()
