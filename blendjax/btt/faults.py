"""Fault policy for fleet RPCs: retries, backoff, deadlines, circuit breaking.

The reference has no fault model at all — a single ``zmq.Again`` surfaces
as ``TimeoutError`` and the caller's training loop dies (SURVEY.md §5 "No
restart, no elasticity").  This module is the one place retry semantics
live for the consumer side: :class:`FaultPolicy` describes *how hard to
try* (attempts, exponential backoff with deterministic jitter, an overall
per-call deadline) and *when to stop trying* (a circuit breaker that opens
after K consecutive failures and rejects calls until a cooldown elapses),
and :meth:`FaultPolicy.run` executes any callable under those rules.

Consumers: :meth:`blendjax.btt.env.RemoteEnv._reqrep` (single env) and
:class:`blendjax.btt.envpool.EnvPool` (pipelined exchange + quarantine
probes).  Every retry/timeout/circuit event increments a named counter in
an :class:`blendjax.utils.timing.EventCounters` (the process-wide
``fleet_counters`` by default) so ``FleetSupervisor.health()`` can report
fleet behavior without log scraping.

Determinism: jitter comes from a ``random.Random`` seeded per
:class:`FaultState` from ``(policy.seed, key)``, so two runs of the same
fault schedule produce the same backoff sequence — the chaos tests rely
on this.

Caveat for non-idempotent RPCs: a retry *re-sends* the request.  For
``reset``/probe traffic that is idempotent; for ``step`` the re-send
carries the SAME correlation id (``wire.BTMID_KEY`` — ``RemoteEnv``
stamps it whenever a policy is attached, the pipelined ``EnvPool``
always), so a producer-side :class:`~blendjax.btb.env.RemoteControlledAgent`
that already simulated the frame re-serves its cached reply instead of
stepping twice — the retry is exactly-once at the simulation level.
Third-party producers that ignore the id keep the old behavior (a
slow-but-alive producer can advance one extra frame; the stale reply is
dropped by REQ_CORRELATE); fleets of those that cannot tolerate it
should run ``FaultPolicy(max_retries=0)`` and rely on quarantine +
re-admission alone.
"""

from __future__ import annotations

import random
import time

from blendjax.obs.flight import flight_recorder
from blendjax.utils.timing import fleet_counters


class CircuitOpenError(TimeoutError):
    """Raised (without attempting the call) while a circuit is open.

    Subclasses :class:`TimeoutError` so callers treating timeouts as
    retriable-later handle circuit rejections the same way.
    """


class FaultState:
    """Mutable per-target state a :class:`FaultPolicy` operates on: the
    consecutive-failure count driving the circuit breaker, plus the
    deterministic jitter stream.  One state per remote target (per env of
    a pool, per ``RemoteEnv``); the policy itself stays immutable and
    shareable."""

    def __init__(self, policy, key=0):
        self.policy = policy
        self.consecutive_failures = 0
        self.open_until = 0.0  # monotonic time the circuit re-closes
        self._rng = random.Random((policy.seed, key).__hash__())

    def backoff(self, attempt):
        """Delay before retry ``attempt`` (1-based): exponential, capped,
        with deterministic multiplicative jitter."""
        p = self.policy
        base = min(p.backoff_max, p.backoff_base * (p.backoff_factor ** (attempt - 1)))
        if p.jitter <= 0:
            return base
        return base * (1.0 + self._rng.uniform(-p.jitter, p.jitter))

    def circuit_open(self, now=None):
        """True while calls should be rejected outright."""
        if self.open_until <= 0:
            return False
        now = self.policy._clock() if now is None else now
        return now < self.open_until

    def record_success(self):
        self.consecutive_failures = 0
        self.open_until = 0.0

    def record_failure(self, counters=None):
        """Count one failure; returns True when this failure opened the
        circuit."""
        self.consecutive_failures += 1
        p = self.policy
        if (
            p.circuit_threshold > 0
            and self.consecutive_failures >= p.circuit_threshold
            and not self.circuit_open()
        ):
            self.open_until = p._clock() + p.circuit_cooldown_s
            if counters is not None:
                counters.incr("circuit_opens")
            return True
        return False


class FaultPolicy:
    """How hard to retry a fleet RPC, and when to give up on a target.

    Params
    ------
    max_retries: int
        Retries after the first attempt (0 = single attempt, the
        reference behavior).
    backoff_base / backoff_factor / backoff_max: float
        Retry ``n`` (1-based) sleeps ``base * factor**(n-1)`` seconds,
        capped at ``backoff_max``.
    jitter: float
        Multiplicative jitter fraction (0.25 = ±25%), drawn from the
        per-state deterministic RNG.
    deadline_s: float | None
        Overall wall-clock budget for one logical call including retries
        and backoff; also the per-attempt wait :class:`EnvPool` uses for
        its pipelined recv when set.  None defers to the caller's socket
        timeout.
    circuit_threshold: int
        Consecutive failures that open the circuit (0 disables).
    circuit_cooldown_s: float
        How long an open circuit rejects calls before allowing one
        half-open trial.
    seed: int
        Seeds the jitter stream (per-state, via ``(seed, key)``).
    """

    def __init__(
        self,
        max_retries=1,
        backoff_base=0.05,
        backoff_factor=2.0,
        backoff_max=2.0,
        jitter=0.25,
        deadline_s=None,
        circuit_threshold=5,
        circuit_cooldown_s=5.0,
        seed=0,
        _clock=time.monotonic,
    ):
        if max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        self.max_retries = max_retries
        self.backoff_base = backoff_base
        self.backoff_factor = backoff_factor
        self.backoff_max = backoff_max
        self.jitter = jitter
        self.deadline_s = deadline_s
        self.circuit_threshold = circuit_threshold
        self.circuit_cooldown_s = circuit_cooldown_s
        self.seed = seed
        self._clock = _clock  # injectable for deterministic tests

    def new_state(self, key=0):
        return FaultState(self, key=key)

    def run(
        self,
        fn,
        state=None,
        counters=None,
        name="rpc",
        retryable=(TimeoutError,),
        sleep=time.sleep,
    ):
        """Execute ``fn(attempt)`` under this policy.

        ``fn`` is called with the 0-based attempt number; any exception in
        ``retryable`` triggers retry/backoff, anything else propagates
        immediately.  Raises the last retryable error when attempts (or
        the deadline) are exhausted, or :class:`CircuitOpenError` without
        calling ``fn`` while the state's circuit is open.
        """
        state = state or self.new_state()
        counters = fleet_counters if counters is None else counters
        now = self._clock()
        if state.circuit_open(now):
            counters.incr("circuit_rejections")
            raise CircuitOpenError(
                f"{name}: circuit open after "
                f"{state.consecutive_failures} consecutive failures "
                f"(cooldown {self.circuit_cooldown_s}s)"
            )
        deadline = None if self.deadline_s is None else now + self.deadline_s
        attempt = 0
        while True:
            try:
                result = fn(attempt)
            except retryable as exc:
                # flight-recorder annotations ride the failure path only
                # (retries already pay a backoff sleep), so the ring
                # costs nothing while the fleet is healthy
                if state.record_failure(counters):
                    flight_recorder.note(
                        "circuit_open", target=name,
                        consecutive_failures=state.consecutive_failures,
                        cooldown_s=self.circuit_cooldown_s,
                    )
                counters.incr("timeouts")
                out_of_budget = deadline is not None and (
                    self._clock() >= deadline
                )
                if attempt >= self.max_retries or out_of_budget:
                    counters.incr("failures")
                    flight_recorder.note(
                        "rpc_failure", target=name, attempts=attempt + 1,
                        error=f"{type(exc).__name__}: {exc}",
                    )
                    raise
                attempt += 1
                counters.incr("retries")
                flight_recorder.note(
                    "retry", target=name, attempt=attempt,
                    error=f"{type(exc).__name__}: {exc}",
                )
                delay = state.backoff(attempt)
                if deadline is not None:
                    delay = min(delay, max(0.0, deadline - self._clock()))
                if delay > 0:
                    sleep(delay)
                continue
            state.record_success()
            return result
