"""Pluggable viewers for ``RemoteEnv.render(mode='human')``
(reference ``btt/env_rendering.py:6-76``).

The reference preferred gym's pyglet viewer with matplotlib fallback; the
pyglet path is legacy (removed from modern gym), so blendjax ships the
matplotlib backend plus the same registry so users can plug their own.
"""

from __future__ import annotations

#: name -> class; first importable entry wins when backend=None
RENDER_BACKENDS = {}


def register_backend(name, cls):
    RENDER_BACKENDS[name] = cls


def create_renderer(backend=None):
    """Instantiate a viewer; ``backend=None`` picks the first usable one."""
    if backend is not None:
        return RENDER_BACKENDS[backend]()
    errors = []
    for name, cls in RENDER_BACKENDS.items():
        try:
            return cls()
        except ImportError as e:  # try the next backend
            errors.append(f"{name}: {e}")
    raise ImportError(
        "No usable render backend; install matplotlib. Tried: " + "; ".join(errors)
    )


class MatplotlibRenderer:
    """Interactive imshow window updated per frame
    (reference ``env_rendering.py:29-52``)."""

    def __init__(self):
        import matplotlib.pyplot as plt

        self._plt = plt
        plt.ion()
        self.fig, self.ax = plt.subplots()
        self.ax.set_axis_off()
        self.img = None

    def imshow(self, rgb):
        if self.img is None:
            self.img = self.ax.imshow(rgb)
        else:
            self.img.set_data(rgb)
        self.fig.canvas.draw_idle()
        self._plt.pause(0.001)

    def close(self):
        self._plt.close(self.fig)


register_backend("matplotlib", MatplotlibRenderer)
