"""Numpy batch collation — the torch ``default_collate`` role, but producing
plain numpy pytrees ready for ``jax.device_put`` (no torch dependency).

Rules: a list of dicts becomes a dict of stacked leaves; ndarrays stack on a
new leading axis; numeric scalars become 1-D arrays; strings/bytes and
ragged leaves stay Python lists.

Role in the arena pipeline: the zero-copy batch assembly
(``_BatchBuilder`` in :mod:`blendjax.btt.dataset`) scatters fixed-shape
array leaves straight into recycled batch buffers and routes everything
it cannot scatter — ragged leaves, mixed-dtype columns, non-array values,
compat-pickle containers — through :func:`collate`, so these rules remain
the single source of truth for batch semantics on BOTH paths (parity is
locked by ``tests/test_arena.py``).
"""

from __future__ import annotations

import numbers

import numpy as np

try:
    from blendjax.native.ring import fast_stack as _fast_stack
except Exception:  # pragma: no cover - native package unavailable
    _fast_stack = None

#: Leaves at or above this many bytes stack via the native GIL-released
#: gather; below it, ctypes call overhead beats the copy cost.
_NATIVE_STACK_MIN_BYTES = 64 * 1024


def _stack(items):
    first = items[0]
    if (
        _fast_stack is not None
        and first.nbytes >= _NATIVE_STACK_MIN_BYTES
        and all(it.dtype == first.dtype for it in items[1:])
    ):
        return _fast_stack(items)
    return np.stack(items)  # handles mixed dtypes via upcast


def collate(items):
    """Collate a non-empty list of samples into one batched pytree."""
    if not items:
        raise ValueError("cannot collate an empty batch")
    elem = items[0]
    if isinstance(elem, dict):
        return {k: collate([it[k] for it in items]) for k in elem}
    if isinstance(elem, tuple):
        return tuple(collate(list(vals)) for vals in zip(*items))
    if isinstance(elem, list):
        return [collate(list(vals)) for vals in zip(*items)]
    if isinstance(elem, np.ndarray):
        if any(it.shape != elem.shape for it in items[1:]):
            return list(items)  # ragged: leave unstacked
        return _stack(items)
    if isinstance(elem, numbers.Number) and not isinstance(elem, bool):
        return np.asarray(items)
    if isinstance(elem, bool):
        return np.asarray(items, dtype=bool)
    return list(items)
