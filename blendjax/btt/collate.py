"""Numpy batch collation — the torch ``default_collate`` role, but producing
plain numpy pytrees ready for ``jax.device_put`` (no torch dependency).

Rules: a list of dicts becomes a dict of stacked leaves; ndarrays stack on a
new leading axis; numeric scalars become 1-D arrays; strings/bytes and
ragged leaves stay Python lists.
"""

from __future__ import annotations

import numbers

import numpy as np


def collate(items):
    """Collate a non-empty list of samples into one batched pytree."""
    if not items:
        raise ValueError("cannot collate an empty batch")
    elem = items[0]
    if isinstance(elem, dict):
        return {k: collate([it[k] for it in items]) for k in elem}
    if isinstance(elem, tuple):
        return tuple(collate(list(vals)) for vals in zip(*items))
    if isinstance(elem, list):
        return [collate(list(vals)) for vals in zip(*items)]
    if isinstance(elem, np.ndarray):
        if any(it.shape != elem.shape for it in items[1:]):
            return list(items)  # ragged: leave unstacked
        return np.stack(items)
    if isinstance(elem, numbers.Number) and not isinstance(elem, bool):
        return np.asarray(items)
    if isinstance(elem, bool):
        return np.asarray(items, dtype=bool)
    return list(items)
