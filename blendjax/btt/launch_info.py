"""Serializable launch metadata (reference ``btt/launch_info.py:4-63``).

``LaunchInfo`` carries the socket addresses (grouped by name), the spawn
command lines, and — when locally launched — the process handles.  The JSON
round-trip is the multi-machine handoff: launch Blender fleets on host A via
``blendjax-launch``, ship ``launch_info.json`` to host B, connect a
``RemoteIterableDataset`` to ``info.addresses['DATA']``.

Fixes the reference's latent ``nullcontext`` NameError on the file-like-
object path (``launch_info.py:38`` uses it without importing it).
"""

from __future__ import annotations

import json
from contextlib import nullcontext


class LaunchInfo:
    """Addresses, commands (argv lists, Popen-ready) and (optionally)
    process handles of a launch."""

    def __init__(self, addresses, commands, processes=None):
        self.addresses = dict(addresses)
        self.commands = list(commands)
        self.processes = processes

    def __repr__(self):
        return f"LaunchInfo(addresses={self.addresses!r})"

    @staticmethod
    def save_json(file, launch_info):
        """Write addresses+commands as JSON to a path or file-like object."""
        ctx = (
            nullcontext(file)
            if hasattr(file, "write")
            else open(file, "w", encoding="utf-8")
        )
        with ctx as fp:
            json.dump(
                {
                    "addresses": launch_info.addresses,
                    "commands": launch_info.commands,
                },
                fp,
                indent=2,
            )

    @staticmethod
    def load_json(file) -> "LaunchInfo":
        """Read a :class:`LaunchInfo` from a path or file-like object."""
        ctx = (
            nullcontext(file)
            if hasattr(file, "read")
            else open(file, "r", encoding="utf-8")
        )
        with ctx as fp:
            data = json.load(fp)
        return LaunchInfo(data["addresses"], data["commands"])
