"""The exactly-once DEALER RPC discipline — one copy, two tiers.

Both wire client tiers — the replay storage client
(:class:`blendjax.replay.shard_client.ShardClient`) and the serving
client (:class:`blendjax.serve.client.ServeClient`) — speak the same
request protocol: stamp a fresh ``wire.BTMID_KEY`` correlation id,
optionally a span context, send over a DEALER socket, poll for the
reply whose id matches (dropping mismatches as stale — a previous
attempt's late reply, or a dead server incarnation's leftovers), raise
on a remote ``error`` reply, and run the whole attempt under a
:class:`~blendjax.btt.faults.FaultPolicy` whose retries re-send the
SAME id so the server's reply cache makes them exactly-once.

That discipline used to live as two ~50-line near-copies that had to
be bug-fixed in lockstep; :func:`exactly_once_rpc` is the single
implementation, parameterized by the caller's naming (error text,
span label/category, policy target name) and error class.
"""

from __future__ import annotations

import time

from blendjax import wire
from blendjax.btt.faults import CircuitOpenError
from blendjax.obs.spans import make_span, now_us


def exactly_once_rpc(socket_fn, msg, *, policy, state, counters,
                     wait_ms, raw_buffers=False, spans=None,
                     remote_name, span_label, span_cat, span_args=None,
                     rpc_name, exc_factory, retryable, pop_mid=False):
    """One exactly-once RPC; returns the decoded reply dict.

    Params
    ------
    socket_fn: callable
        Zero-arg callable returning either the (lazily dialed) DEALER
        socket, or a transport channel
        (:class:`blendjax.btt.transport.RpcChannel`): anything with
        ``send_request``/``poll_reply``/``recv_reply`` — which is how
        the same discipline rides the shm transport unchanged
        (docs/transport.md).
    msg: dict
        The request, ``cmd`` included; stamped with a fresh correlation
        id here (a fault-policy retry re-sends the SAME stamped dict).
    policy / state / counters:
        The caller's :class:`FaultPolicy`, its per-target
        :class:`FaultState`, and the counter sink (``stale_replies``
        and the policy's retry/timeout counters land there).
    wait_ms: int
        Per-attempt reply deadline.
    spans: SpanRecorder | None
        When set, the request carries a span context and the reply's
        piggybacked server spans are ingested alongside a client-side
        ``{span_label}:{cmd}`` span (category ``span_cat``).
    remote_name: str
        Names the remote in remote-failure text.
    rpc_name: str
        The fault-policy call name (flight-recorder / counter label).
    exc_factory: callable
        ``exc_factory(message) -> Exception`` building the caller's
        transport error (must be in ``retryable``).
    retryable: tuple
        Exception classes the policy retries.
    pop_mid: bool
        Strip the echoed correlation id from the returned reply.
    """
    import zmq

    cmd = msg.get("cmd")
    mid = wire.stamp_message_id(msg)
    if spans is not None:
        wire.stamp_span_context(msg, mid)
    t0_us = now_us() if spans is not None else 0

    def attempt(n):
        sock = socket_fn()
        channel = hasattr(sock, "send_request")
        if channel:
            sock.send_request(msg, raw_buffers=raw_buffers)
        else:
            wire.send_message_dealer(sock, msg, raw_buffers=raw_buffers)
        deadline = time.monotonic() + wait_ms / 1000.0
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                if channel:
                    # over shm a missed deadline is the peer-death
                    # signal: demote so the retry rides ZMQ
                    sock.notify_timeout()
                raise exc_factory(
                    f"no reply to {cmd!r} within {wait_ms} ms "
                    f"(attempt {n + 1})"
                )
            slice_ms = max(1, min(50, int(remaining * 1000)))
            if channel:
                reply = (sock.recv_reply()
                         if sock.poll_reply(slice_ms) else None)
                if reply is None:
                    continue  # spurious wakeup (wrap marker / dropped)
            else:
                if not sock.poll(slice_ms, zmq.POLLIN):
                    continue
                reply = wire.recv_message_dealer(sock)
            if reply.get(wire.BTMID_KEY) != mid:
                # a previous attempt's late reply (or a dead
                # incarnation's): this request's reply is still
                # owed — keep waiting
                counters.incr("stale_replies")
                continue
            piggyback = wire.pop_spans(reply)
            if spans is not None:
                spans.ingest(piggyback)
                spans.record(make_span(
                    f"{span_label}:{cmd}", t0_us, trace=mid,
                    cat=span_cat, args=span_args,
                ))
            if "error" in reply:
                raise RuntimeError(
                    f"{remote_name}: {cmd!r} failed remotely: "
                    f"{reply['error']}"
                )
            if pop_mid:
                reply.pop(wire.BTMID_KEY, None)
            return reply

    try:
        return policy.run(
            attempt, state=state, counters=counters, name=rpc_name,
            retryable=retryable,
        )
    except CircuitOpenError as exc:
        raise exc_factory(str(exc)) from exc
