"""blendjax.btt — consumer-side package (training host, JAX).

Mirrors the reference's ``blendtorch.btt`` surface
(``pkg_pytorch/blendtorch/btt/__init__.py:1-8``) but torch-free: the
DataLoader role is played by :class:`blendjax.btt.loader.BatchLoader` plus
the double-buffered device feed in :mod:`blendjax.btt.prefetch`.  Attribute
access is lazy so importing the package never drags in jax (only the device
feed and env-pool modules need it).
"""

__version__ = "0.1.0"

_LAZY = {
    "BlenderLauncher": ("blendjax.btt.launcher", "BlenderLauncher"),
    "discover_blender": ("blendjax.btt.finder", "discover_blender"),
    "LaunchInfo": ("blendjax.btt.launch_info", "LaunchInfo"),
    "RemoteIterableDataset": ("blendjax.btt.dataset", "RemoteIterableDataset"),
    "SingleFileDataset": ("blendjax.btt.dataset", "SingleFileDataset"),
    "FileDataset": ("blendjax.btt.dataset", "FileDataset"),
    "FileRecorder": ("blendjax.btt.file", "FileRecorder"),
    "FileReader": ("blendjax.btt.file", "FileReader"),
    "DuplexChannel": ("blendjax.btt.duplex", "DuplexChannel"),
    "BatchLoader": ("blendjax.btt.loader", "BatchLoader"),
    "collate": ("blendjax.btt.collate", "collate"),
    "ArenaPool": ("blendjax.btt.arena", "ArenaPool"),
    "ArenaBatch": ("blendjax.btt.arena", "ArenaBatch"),
    "device_prefetch": ("blendjax.btt.prefetch", "device_prefetch"),
    "JaxStream": ("blendjax.btt.prefetch", "JaxStream"),
    "RemoteEnv": ("blendjax.btt.env", "RemoteEnv"),
    "launch_env": ("blendjax.btt.env", "launch_env"),
    "OpenAIRemoteEnv": ("blendjax.btt.env", "OpenAIRemoteEnv"),
    "EnvPool": ("blendjax.btt.envpool", "EnvPool"),
    "BlenderVectorEnv": ("blendjax.btt.vector_env", "BlenderVectorEnv"),
    "launch_vector_env": ("blendjax.btt.vector_env", "launch_vector_env"),
    "FleetWatchdog": ("blendjax.btt.watchdog", "FleetWatchdog"),
    "FleetSupervisor": ("blendjax.btt.supervise", "FleetSupervisor"),
    "FaultPolicy": ("blendjax.btt.faults", "FaultPolicy"),
    "CircuitOpenError": ("blendjax.btt.faults", "CircuitOpenError"),
    "ChaosProxy": ("blendjax.btt.chaos", "ChaosProxy"),
    "ShmChaos": ("blendjax.btt.shm_rpc", "ShmChaos"),
    "RpcChannel": ("blendjax.btt.transport", "RpcChannel"),
    "get_primary_ip": ("blendjax.btt.utils", "get_primary_ip"),
}

_LAZY_MODULES = (
    "launcher",
    "finder",
    "launch_info",
    "arena",
    "dataset",
    "file",
    "duplex",
    "loader",
    "collate",
    "prefetch",
    "env",
    "envpool",
    "vector_env",
    "env_rendering",
    "watchdog",
    "supervise",
    "faults",
    "chaos",
    "torch_compat",
    "shm_rpc",
    "transport",
    "rpc",
    "utils",
    "constants",
    "apps",
)


def __getattr__(name):
    import importlib

    if name in _LAZY:
        module, attr = _LAZY[name]
        value = getattr(importlib.import_module(module), attr)
        globals()[name] = value
        return value
    if name in _LAZY_MODULES:
        mod = importlib.import_module(f"blendjax.btt.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'blendjax.btt' has no attribute {name!r}")


def __dir__():
    return sorted(set(list(globals()) + list(_LAZY) + list(_LAZY_MODULES)))
