"""Streaming and replay datasets (reference ``btt/dataset.py:14-153``),
re-designed torch-free.

``RemoteIterableDataset`` pulls message dicts from N Blender producers over
a fan-in PULL socket (fair-queued across producers, HWM backpressure).  The
reference couples worker parallelism to ``torch.utils.data`` worker
processes; blendjax makes the split explicit — ``stream(worker_id,
num_workers, ...)`` — so any executor (threads in
:class:`blendjax.btt.loader.BatchLoader`, torch DataLoader workers via the
compat shim, or one stream per TPU host via ``shard``) can drive it.

Sharding semantics match the reference: each worker yields
``max_items // num_workers`` items (``dataset.py:97``), generalized to
``num_shards`` host-level shards for multi-host TPU slices (SURVEY.md §7
"multi-host sharding semantics").
"""

from __future__ import annotations

import bisect
import numbers
import pickle
import sys
import time
from contextlib import ExitStack
from glob import glob

import zmq

from blendjax import wire
from blendjax.btt.constants import DEFAULT_TIMEOUTMS
from blendjax.btt.file import FileReader, FileRecorder
from blendjax.utils.timing import fleet_counters


def _identity(x):
    return x


def _torch_worker_info():
    """(worker_id, num_workers) when called inside a torch DataLoader worker.

    Import-free unless torch is already loaded: keeps the consumer package
    torch-independent while letting reference-style DataLoader use keep
    working.
    """
    utils_data = sys.modules.get("torch.utils.data")
    if utils_data is None:
        return None
    wi = utils_data.get_worker_info()
    if wi is None:
        return None
    return wi.id, wi.num_workers


class RemoteIterableDataset:
    """Iterable over message dicts streamed from remote Blender instances.

    Params
    ------
    addresses: list[str]
        Producer addresses to connect to (fan-in over all of them).
    queue_size: int
        RCVHWM; producers stall once this many messages are in flight.
    timeoutms: int
        Max silence before :class:`TimeoutError`.
    max_items: int
        Artificial dataset length (and recorder capacity).
    item_transform: callable | None
        Applied to each received dict.
    record_path_prefix: str | None
        When set, worker ``w`` records raw messages to
        ``{prefix}_{w:02d}.btr`` while streaming.
    counters: EventCounters | None
        Sink for ``stream_timeouts`` / ``stream_ring_vanished`` events;
        defaults to the process-wide
        ``blendjax.utils.timing.fleet_counters``.  Pass the same instance
        as the fleet's ``FleetSupervisor`` for isolated per-fleet
        accounting in ``health()``.
    """

    def __init__(
        self,
        addresses,
        queue_size=10,
        timeoutms=DEFAULT_TIMEOUTMS,
        max_items=100000,
        item_transform=None,
        record_path_prefix=None,
        counters=None,
    ):
        self.addresses = list(addresses)
        self.queue_size = queue_size
        self.timeoutms = timeoutms
        self.max_items = max_items
        self.record_path_prefix = record_path_prefix
        self.item_transform = item_transform or _identity
        self.counters = counters if counters is not None else fleet_counters

    def enable_recording(self, fname):
        """Record while streaming; set before iteration starts."""
        self.record_path_prefix = fname

    def stream_length(self, max_items):
        """Set the artificial dataset length."""
        self.max_items = max_items

    def __iter__(self):
        wi = _torch_worker_info()
        if wi is not None:
            return self.stream(worker_id=wi[0], num_workers=wi[1])
        return self.stream()

    def stream(
        self,
        worker_id=0,
        num_workers=1,
        shard_id=0,
        num_shards=1,
        stop_event=None,
    ):
        """Generator yielding ``max_items // (num_workers * num_shards)``
        transformed items for this (shard, worker).

        ``stop_event`` (a ``threading.Event``) aborts the stream promptly —
        the poll loop checks it between messages so loaders can shut down
        without waiting out ``timeoutms``.

        ``shm://`` addresses take the native shared-memory path (see
        :mod:`blendjax.native.ring`): rings are single-consumer, so they are
        partitioned ``addresses[worker_id::num_workers]`` instead of the
        ZMQ connect-to-all fan-in; use ``num_workers <= len(addresses)``.
        """
        if self.addresses and all(a.startswith("shm://") for a in self.addresses):
            yield from self._stream_shm(
                worker_id, num_workers, shard_id, num_shards, stop_event
            )
            return
        ctx = zmq.Context.instance()
        socket = ctx.socket(zmq.PULL)
        socket.setsockopt(zmq.RCVHWM, self.queue_size)
        socket.setsockopt(zmq.LINGER, 0)
        try:
            for addr in self.addresses:
                socket.connect(addr)
            poller = zmq.Poller()
            poller.register(socket, zmq.POLLIN)

            count = self.max_items // (num_workers * num_shards)
            global_worker = shard_id * num_workers + worker_id
            with ExitStack() as es:
                rec = None
                if self.record_path_prefix is not None:
                    rec = es.enter_context(
                        FileRecorder(
                            FileRecorder.filename(
                                self.record_path_prefix, global_worker
                            ),
                            self.max_items,
                        )
                    )
                for _ in range(count):
                    if not self._poll_message(poller, stop_event):
                        return
                    if rec is not None:
                        frames = wire.recv_message_raw(socket)
                        rec.save_frames(frames)
                        obj = wire.decode_raw_frames(frames)
                    else:
                        obj = wire.recv_message(socket)
                    yield self._item(obj)
        finally:
            socket.close(0)

    def _poll_message(self, poller, stop_event):
        """Wait for the next message on the PULL socket: True when one is
        ready, False when ``stop_event`` fired; raises TimeoutError after
        ``timeoutms`` of silence.  Shared by the per-item and batched
        ZMQ stream loops so the timeout/stop semantics cannot drift."""
        waited = 0
        slice_ms = 100 if stop_event is not None else self.timeoutms
        while True:
            if stop_event is not None and stop_event.is_set():
                return False
            if poller.poll(min(slice_ms, self.timeoutms)):
                return True
            waited += slice_ms
            if waited >= self.timeoutms:
                self.counters.incr("stream_timeouts")
                raise TimeoutError(
                    f"No message within {self.timeoutms} ms from "
                    f"{self.addresses}"
                )

    def _shm_rotation(self, worker_id, num_workers, stop_event, consume, count):
        """Shared ring-rotation loop for the shm paths: opens this worker's
        rings, round-robins ``consume(reader, block_ms)`` over them, and
        owns the EOF / timeout / stop semantics.  ``consume`` returns a
        result to yield, None when no message arrived in its slice, or
        raises EOFError when its ring is closed+drained (the ring then
        leaves the rotation; producer exit ends the stream instead of
        raising a timeout)."""
        from blendjax.native import ShmRingReader

        mine = self.addresses[worker_id::num_workers]
        if not mine:
            return
        # ring creation waits on producer startup: give it the stream timeout
        open_ms = max(self.timeoutms, 10000)
        readers = [ShmRingReader(a, open_timeout_ms=open_ms) for a in mine]
        try:
            delivered = 0
            waited_ms = 0
            # single ring (the common case: one worker per producer):
            # block inside the C call, 100 us wakeups.  Multi-ring:
            # non-blocking rotation with a short host-side sleep.
            block_ms = 100 if len(readers) == 1 else 0
            while delivered < count and readers:
                progressed = False
                for reader in list(readers):
                    if stop_event is not None and stop_event.is_set():
                        return
                    try:
                        res = consume(reader, block_ms)
                    except EOFError:
                        reader.close(unlink=True)  # drained + closed
                        readers.remove(reader)
                        block_ms = 100 if len(readers) == 1 else 0
                        continue
                    except ConnectionResetError:
                        # ring vanished (rc -4) and the producer isn't back
                        # within this slice; the reader stays retryable, so
                        # keep rotating until the dataset timeout expires
                        # (the watchdog respawn may land any moment)
                        self.counters.incr("stream_ring_vanished")
                        waited_ms += max(block_ms, 0)
                        continue
                    if res is None:
                        waited_ms += max(block_ms, 0)
                        continue
                    progressed = True
                    waited_ms = 0
                    yield res
                    delivered += 1
                    if delivered >= count:
                        return
                if not progressed:
                    if block_ms == 0:
                        time.sleep(0.001)
                        waited_ms += 1
                    if waited_ms >= self.timeoutms:
                        self.counters.incr("stream_timeouts")
                        raise TimeoutError(
                            f"No message within {self.timeoutms} ms from {mine}"
                        )
        finally:
            for r in readers:
                r.close()

    def _stream_shm(self, worker_id, num_workers, shard_id, num_shards, stop_event):
        """Native-transport variant of the stream loop (per-item)."""
        count = self.max_items // (num_workers * num_shards)
        with ExitStack() as es:
            rec = None
            if self.record_path_prefix is not None:
                rec = es.enter_context(
                    FileRecorder(
                        FileRecorder.filename(
                            self.record_path_prefix,
                            shard_id * num_workers + worker_id,
                        ),
                        self.max_items,
                    )
                )

            def consume(reader, block_ms):
                frames = reader.recv_frames(timeout_ms=block_ms)
                if frames is None:
                    return None
                if rec is not None:
                    rec.save_frames(frames)
                return (self._item(wire.decode(frames)),)

            for (item,) in self._shm_rotation(
                worker_id, num_workers, stop_event, consume, count
            ):
                yield item

    def _item(self, item):
        """Override point; defaults to ``item_transform`` (reference
        ``dataset.py:113-117``)."""
        return self.item_transform(item)

    # -- batched zero-intermediate-copy path (shm + zmq transports) --------

    def supports_batched_stream(self):
        """True when :meth:`stream_batches` can assemble batches straight
        from the wire frames (no recording, no per-item transform) —
        both the native shm transport and the ZMQ fan-in qualify."""
        return (
            bool(self.addresses)
            and self.record_path_prefix is None
            and self.item_transform is _identity
            and type(self)._item is RemoteIterableDataset._item
        )

    def stream_batches(
        self,
        batch_size,
        worker_id=0,
        num_workers=1,
        shard_id=0,
        num_shards=1,
        stop_event=None,
        drop_last=True,
        timer=None,
        arena_pool=None,
    ):
        """Yield collated batches, bypassing per-item materialization.

        Array payloads are scattered **directly into preallocated batch
        buffers at their final batch offset** instead of the per-item
        view + ``collate`` stack:

        - shm transport: each ring record is held open just long enough
          to memcpy its payloads into the batch buffers
          (``recv_frames_view`` + ``copy_into``, GIL released) — one
          copy, no intermediate allocations;
        - ZMQ transport: raw-buffer frames are referenced until the
          batch completes, then gathered per leaf in ONE GIL-released
          native call (``gather_into``) straight into the batch buffer
          — the ``np.frombuffer`` view + ``np.stack`` copy of the
          legacy path disappears entirely.

        ``arena_pool`` (an :class:`blendjax.btt.arena.ArenaPool`)
        recycles the batch buffers themselves: batches are then yielded
        as :class:`~blendjax.btt.arena.ArenaBatch` and the consumer
        (normally the device prefetcher) recycles each arena once its
        transfer completes — pool exhaustion backpressures the stream
        (``arena_wait`` stage) instead of growing host memory.

        Falls back to ``stream()`` + collate when
        :meth:`supports_batched_stream` is False (recording or per-item
        transforms active).  Schema drift between messages (changed
        shape/dtype for a key), ragged leaves, and compat-pickle
        messages degrade per key to the generic collate rules instead
        of failing the stream — existing producers keep working
        unmodified.
        """
        from blendjax.btt.collate import collate as default_collate

        if timer is None:
            from blendjax.utils.timing import StageTimer

            timer = StageTimer()
        if not self.supports_batched_stream():
            batch = []
            for item in self.stream(
                worker_id=worker_id,
                num_workers=num_workers,
                shard_id=shard_id,
                num_shards=num_shards,
                stop_event=stop_event,
            ):
                batch.append(item)
                if len(batch) == batch_size:
                    with timer.stage("collate"):
                        out = default_collate(batch)
                    yield out
                    batch = []
            if batch and not drop_last:
                with timer.stage("collate"):
                    out = default_collate(batch)
                yield out
            return

        if all(a.startswith("shm://") for a in self.addresses):
            impl = self._stream_shm_batches
        else:
            impl = self._stream_zmq_batches
        yield from impl(
            batch_size,
            worker_id,
            num_workers,
            shard_id,
            num_shards,
            stop_event,
            drop_last,
            timer,
            arena_pool,
        )

    def _acquire_arena(self, arena_pool, timer, stop_event):
        """Next free arena from the pool (None without a pool).  Blocks
        under pool exhaustion — the backpressure seam — accounted to the
        ``arena_wait`` stage.  Raises TimeoutError if no arena frees up
        within the stream timeout (a stuck consumer looks exactly like a
        silent producer to the training loop)."""
        if arena_pool is None:
            return None, True
        with timer.stage("arena_wait"):
            arena = arena_pool.acquire(
                timeout=self.timeoutms / 1e3, stop_event=stop_event
            )
        if arena is None:
            if stop_event is not None and stop_event.is_set():
                return None, False
            raise TimeoutError(
                f"no batch arena freed within {self.timeoutms} ms "
                f"(pool size {arena_pool.pool_size}); the consumer has "
                "stalled or the pool is undersized"
            )
        return arena, True

    def _wrap_batch(self, data, arena):
        if arena is None:
            return data
        from blendjax.btt.arena import ArenaBatch

        return ArenaBatch(data, arena)

    def _stream_zmq_batches(
        self,
        batch_size,
        worker_id,
        num_workers,
        shard_id,
        num_shards,
        stop_event,
        drop_last,
        timer,
        arena_pool,
    ):
        """Batched ZMQ fan-in: decode each multipart message's frames
        straight into the (optionally pooled) batch buffers — the
        deferred :class:`_BatchBuilder` mode keeps the zero-copy frame
        views alive until the batch completes, then gathers each leaf in
        one GIL-released call."""
        ctx = zmq.Context.instance()
        socket = ctx.socket(zmq.PULL)
        socket.setsockopt(zmq.RCVHWM, self.queue_size)
        socket.setsockopt(zmq.LINGER, 0)
        builder = _BatchBuilder(
            batch_size,
            defer=True,
            schema_cache={},  # decode plan shared across this stream's batches
            parallel=num_workers > 1,
        )
        pending = False  # builder holds an unyielded (possibly empty) batch
        arena = None
        try:
            for addr in self.addresses:
                socket.connect(addr)
            poller = zmq.Poller()
            poller.register(socket, zmq.POLLIN)
            count = self.max_items // (num_workers * num_shards)
            for _ in range(count):
                if not self._poll_message(poller, stop_event):
                    return
                frames = socket.recv_multipart(copy=False)
                if not pending:
                    arena, alive = self._acquire_arena(
                        arena_pool, timer, stop_event
                    )
                    if not alive:
                        return
                    builder.reset(arena)
                    pending = True
                builder.add_message([f.buffer for f in frames])
                if builder.full():
                    with timer.stage("scatter"):
                        data = builder.finish()
                    # drop the batch's zero-copy wire frames NOW — holding
                    # them until the next message would keep the whole
                    # batch's frame buffers alive across the inter-batch
                    # gap (scattered leaves are already copied; ragged
                    # fallback views hold their own frame references)
                    builder.reset()
                    # hand ownership to the batch BEFORE yielding: a
                    # generator closed at the yield would otherwise
                    # double-release the arena from the finally below
                    # while the yielded ArenaBatch still references it
                    out, arena = self._wrap_batch(data, arena), None
                    pending = False
                    yield out
            if pending and builder.count and not drop_last:
                with timer.stage("scatter"):
                    data = builder.finish()
                builder.reset()
                out, arena = self._wrap_batch(data, arena), None
                pending = False
                yield out
        finally:
            if arena is not None:
                arena.release()  # acquired but never yielded (dropped tail)
            socket.close(0)

    def _stream_shm_batches(
        self,
        batch_size,
        worker_id,
        num_workers,
        shard_id,
        num_shards,
        stop_event,
        drop_last,
        timer,
        arena_pool=None,
    ):
        count = self.max_items // (num_workers * num_shards)
        state = {"builder": None, "arena": None}

        def consume(reader, block_ms):
            frames = reader.recv_frames_view(timeout_ms=block_ms)
            if frames is None:
                return None
            try:
                if state["builder"] is None:
                    arena, alive = self._acquire_arena(
                        arena_pool, timer, stop_event
                    )
                    if not alive:
                        # stream stopping mid-acquire: drop this record and
                        # let the rotation's own stop check end the stream
                        return None
                    state["arena"] = arena
                    state["builder"] = _BatchBuilder(batch_size, arena=arena)
                with timer.stage("scatter"):
                    state["builder"].add_message(frames)
            finally:
                reader.release_record()
            return True

        try:
            for _ in self._shm_rotation(
                worker_id, num_workers, stop_event, consume, count
            ):
                builder = state["builder"]
                if builder is not None and builder.full():
                    with timer.stage("scatter"):
                        data = builder.finish()
                    # ownership moves to the batch BEFORE the yield (a
                    # close at the yield must not re-release the arena)
                    out = self._wrap_batch(data, state["arena"])
                    state["builder"], state["arena"] = None, None
                    yield out
            builder = state["builder"]
            if builder is not None and builder.count and not drop_last:
                with timer.stage("scatter"):
                    data = builder.finish()
                out = self._wrap_batch(data, state["arena"])
                state["builder"], state["arena"] = None, None
                yield out
        finally:
            if state["arena"] is not None:
                state["arena"].release()


class _BatchBuilder:
    """Assembles one collated batch directly from wire frames.

    Array leaves (raw-buffer placeholders or ndarrays in compat pickles)
    land in ``(batch_size, *shape)`` buffers — taken from a recycled
    :class:`blendjax.btt.arena.Arena` when one is supplied, freshly
    allocated otherwise; everything else accumulates in per-key lists
    collated at the end.  Two assembly modes:

    - **eager** (shm transport): each message's payloads are memcpy'd
      into the batch buffer before the ring record is released
      (``copy_into``, GIL released for large frames);
    - **deferred** (``defer=True``, ZMQ transport): zero-copy frame
      views are referenced until the batch completes, then each leaf is
      copied ONCE into the batch buffer — via the GIL-released native
      ``gather_into`` for large frames, ``np.stack(out=...)`` below the
      native threshold — with no intermediate batch allocation.  After
      the first message fixes the schema, later messages are decoded by
      a precompiled per-stream plan (no recursive walk on the hot
      path); any structural surprise falls back to the generic walk for
      that message, preserving collate semantics exactly.

    Semantics mirror the generic ``stream() + collate`` path exactly: a
    key whose shape/dtype drifts mid-batch degrades to the ragged-list
    rules, keys absent from the batch's first message are dropped, and a
    message *missing* a first-message key raises KeyError (as dict
    collate would).
    """

    #: In parallel assembly (several loader workers sharing the GIL) the
    #: scarce resource is GIL time, not wall time: the native GIL-released
    #: gather pays off as soon as its memcpy outweighs the per-source
    #: pointer extraction (~3 us/source) — far below the single-thread
    #: threshold, where the whole copy is on the critical path either way.
    _PARALLEL_GATHER_MIN_BYTES = 16 * 1024

    def __init__(self, batch_size, arena=None, defer=False, schema_cache=None,
                 parallel=False):
        import numpy as np

        self._np = np
        self.batch_size = batch_size
        self.count = 0
        self._arena = arena
        self._defer = bool(defer)
        self._parallel = bool(parallel)
        self._stacked = {}  # eager: path -> preallocated (B, ...) ndarray
        self._lists = {}  # eager: path -> leaves (generic collate at end)
        self._msgs = []  # deferred: per-message frame lists (zero-copy)
        self._paths = None  # schema from the first message
        # deferred: {'schema': {...}} shared across builders of one stream
        # so the decode plan survives batch boundaries
        self._schema_cache = schema_cache if schema_cache is not None else {}

    def full(self):
        return self.count >= self.batch_size

    def reset(self, arena=None):
        """Recycle this builder for the next batch (deferred mode): the
        finished batch owns copies (or collate outputs), so the frame
        references can drop; per-batch state rewinds while the stream's
        schema cache lives on.  Returns self."""
        self.count = 0
        self._arena = arena
        self._msgs.clear()
        self._stacked = {}
        self._lists = {}
        self._paths = None
        return self

    def _batch_buffer(self, path, leaf_shape, dtype):
        shape = (self.batch_size,) + tuple(leaf_shape)
        if self._arena is not None:
            return self._arena.get_buffer(path, shape, dtype)
        return self._np.empty(shape, dtype)

    # -- leaf walking -------------------------------------------------------

    def _view(self, placeholder, payloads):
        """ndarray view into the frame/arena for a raw-buffer leaf."""
        np = self._np
        return np.frombuffer(
            payloads[placeholder[wire.ARRAY_PLACEHOLDER]],
            dtype=np.dtype(placeholder["dtype"]),
        ).reshape(placeholder["shape"])

    def _resolve_copy(self, obj, payloads):
        """Deep-resolve placeholders inside a container to *owned* arrays
        (the shm views die when the record is released; the deferred path
        keeps views since its frames outlive the batch)."""
        np = self._np
        if wire.is_array_placeholder(obj):
            view = self._view(obj, payloads)
            return view if self._defer else np.array(view)
        if isinstance(obj, dict):
            return {k: self._resolve_copy(v, payloads) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            seq = [self._resolve_copy(v, payloads) for v in obj]
            return seq if isinstance(obj, list) else tuple(seq)
        return obj

    def _walk(self, obj, payloads, path=()):
        """Yield (path, leaf, is_array) with raw-buffer placeholders
        resolved to ndarray views over the payload frames.  list/tuple
        containers are resolved and treated as single leaves — the final
        ``collate`` recurses into them exactly like the generic path
        does."""
        np = self._np
        if isinstance(obj, dict):
            if wire.is_array_placeholder(obj):
                yield path, self._view(obj, payloads), True
                return
            for k, v in obj.items():
                yield from self._walk(v, payloads, path + (k,))
            return
        if isinstance(obj, np.ndarray):
            yield path, obj, True
            return
        if isinstance(obj, (list, tuple)):
            yield path, self._resolve_copy(obj, payloads), False
            return
        yield path, obj, False

    # -- deferred columnar decode -------------------------------------------

    def _make_schema(self, head):
        """Precompile ``head``'s structure into a columnar decode plan:
        one entry per leaf — (path, key-chain, kind, shape, dtype-str,
        dtype) — plus the arity of every dict node (so a key added or
        removed anywhere in a later message invalidates the plan instead
        of being silently mis-handled).  Kinds: 'raw' (placeholder ->
        zero-copy payload frame), 'array' (materialized ndarray, compat
        pickles), 'container' (list/tuple, resolved per message), 'leaf'
        (plain value).  A batch that deviates from the plan in ANY way is
        re-processed by the generic per-message walk, so the plan is
        purely a fast path — never a semantics change."""
        np = self._np
        plan = []
        dict_lens = []

        def build(obj, path, keys):
            if isinstance(obj, dict):
                if wire.is_array_placeholder(obj):
                    # the whole placeholder is static per schema (frame
                    # index, dtype string, shape tuple): keep it as a
                    # template so the hot path is ONE dict equality per
                    # message instead of field-by-field checks
                    plan.append((
                        path, keys, "raw",
                        (
                            dict(obj),
                            obj[wire.ARRAY_PLACEHOLDER],
                            tuple(obj["shape"]),
                            np.dtype(obj["dtype"]),
                        ),
                    ))
                    return
                dict_lens.append((keys, len(obj)))
                for k, v in obj.items():
                    build(v, path + (k,), keys + (k,))
                return
            if isinstance(obj, np.ndarray):
                plan.append((path, keys, "array", None))
                return
            if isinstance(obj, (list, tuple)):
                plan.append((path, keys, "container", None))
                return
            plan.append((path, keys, "leaf", None))

        build(head, (), ())
        return {"plan": plan, "dict_lens": dict_lens}

    def _columnar(self, heads, msgs, schema):
        """Collate the batch along the precompiled plan, column by
        column — the hot path.  Returns None on ANY deviation (changed
        arity, moved key, type change, drifted array geometry); the
        caller then re-runs the generic per-message walk, which applies
        the exact legacy collate semantics including per-key degrade."""
        from blendjax.btt.collate import _NATIVE_STACK_MIN_BYTES
        from blendjax.btt.collate import collate as list_collate
        from blendjax.native.ring import gather_into

        np = self._np
        ndarray = np.ndarray
        frombuffer = np.frombuffer
        n = self.count
        try:
            for keys, ln in schema["dict_lens"]:
                nodes = heads
                for k in keys:
                    nodes = [v[k] for v in nodes]
                # arity check alone suffices: a non-mapping impostor that
                # happens to have the right len still fails the leaf
                # traversals below (KeyError/TypeError -> generic walk),
                # matching legacy collate's duck-typed indexing
                if not all(len(v) == ln for v in nodes):
                    return None
            out = {}
            for path, keys, kind, aux in schema["plan"]:
                vals = heads
                for k in keys:
                    vals = [v[k] for v in vals]
                if kind == "raw":
                    template, idx, shape, dtype = aux
                    # one C-level dict equality per message; any spelling
                    # difference (shape as list, drifted geometry, moved
                    # frame index, type change) fails the plan and takes
                    # the generic walk, which normalizes it
                    if not all(
                        type(v) is dict and v == template for v in vals
                    ):
                        return None
                    fi = idx + 1  # payload frames start after the header
                    bufs = [m[fi] for m in msgs]
                    buf = self._batch_buffer(path, shape, dtype)
                    dst = buf if n == self.batch_size else buf[:n]
                    row_bytes = dst.nbytes // n if n else 0
                    min_native = (
                        self._PARALLEL_GATHER_MIN_BYTES
                        if self._parallel
                        else _NATIVE_STACK_MIN_BYTES
                    )
                    if row_bytes >= min_native and not dtype.hasobject:
                        gather_into(dst, bufs)
                    else:
                        rows = dst.reshape(n, -1)
                        for i, b in enumerate(bufs):
                            rows[i] = frombuffer(b, dtype)
                elif kind == "leaf":
                    v0 = vals[0]
                    t0v = type(v0)
                    if all(type(v) is t0v for v in vals):
                        # uniform type (the overwhelming case): one
                        # container check on the representative
                        if isinstance(v0, (dict, ndarray, list, tuple)):
                            return None
                    elif any(
                        isinstance(v, (dict, ndarray, list, tuple))
                        for v in vals
                    ):
                        return None
                    # inlined scalar collate rules (same dispatch order)
                    if isinstance(v0, bool):
                        dst = np.asarray(vals, dtype=bool)
                    elif isinstance(v0, numbers.Number):
                        dst = np.asarray(vals)
                    else:
                        dst = list(vals)
                elif kind == "array":
                    first = vals[0]
                    if not all(
                        isinstance(v, ndarray)
                        and v.shape == first.shape
                        and v.dtype == first.dtype
                        for v in vals
                    ):
                        return None
                    buf = self._batch_buffer(path, first.shape, first.dtype)
                    dst = buf if n == self.batch_size else buf[:n]
                    for i, v in enumerate(vals):
                        dst[i] = v
                else:  # container
                    if not all(isinstance(v, (list, tuple)) for v in vals):
                        return None
                    dst = list_collate([
                        self._resolve_copy(v, msgs[i][1:])
                        for i, v in enumerate(vals)
                    ])
                if len(path) == 1:
                    out[path[0]] = dst
                else:
                    _set_path(out, path, dst)
            return out
        except (KeyError, TypeError, IndexError, ValueError):
            # ValueError covers ambiguous ndarray comparisons from type
            # drift; a genuinely malformed frame re-raises from the
            # generic walk with the legacy error
            return None

    def _generic_deferred(self, heads, payload_lists):
        """Per-message walk fallback for batches the plan cannot decode:
        the exact legacy collate semantics (late-key drop, missing-key
        KeyError, per-key degrade to ragged/upcast rules).  Also rebuilds
        the stream's cached schema from this batch's first message."""
        from blendjax.btt.collate import _NATIVE_STACK_MIN_BYTES
        from blendjax.btt.collate import collate as list_collate
        from blendjax.native.ring import gather_into

        np = self._np
        cols = {}
        paths = None
        for mi, (head, payloads) in enumerate(zip(heads, payload_lists)):
            seen = set()
            for path, leaf, is_array in self._walk(head, payloads):
                if paths is not None and path not in paths:
                    # generic collate keys the batch off its first item and
                    # silently drops keys that only appear later — match it
                    continue
                seen.add(path)
                cols.setdefault(path, []).append((leaf, is_array))
            if paths is None:
                paths = seen
                self._schema_cache["schema"] = self._make_schema(head)
            elif seen != paths:
                # a slot without a value for a first-message key would
                # silently misalign every later slot — fail loudly like
                # dict collate
                missing = sorted(map(str, paths - seen))
                raise KeyError(
                    f"stream message {mi} of the current batch is missing "
                    f"key(s) {missing} present in the batch's first message"
                )
        n = self.count
        out = {}
        for path, col in cols.items():
            if col and all(is_arr for _, is_arr in col):
                first = col[0][0]
                if all(
                    v.shape == first.shape and v.dtype == first.dtype
                    for v, _ in col
                ):
                    buf = self._batch_buffer(path, first.shape, first.dtype)
                    dst = buf if n == self.batch_size else buf[:n]
                    vals = [v for v, _ in col]
                    min_native = (
                        self._PARALLEL_GATHER_MIN_BYTES
                        if self._parallel
                        else _NATIVE_STACK_MIN_BYTES
                    )
                    if (
                        first.nbytes >= min_native
                        and not first.dtype.hasobject
                    ):
                        gather_into(dst, vals)
                    else:
                        np.stack(vals, out=dst)
                    _set_path(out, path, dst)
                    continue
            vals = [v for v, _ in col]
            _set_path(out, path, list_collate(vals) if vals else vals)
        return out

    def add_message(self, frames):
        """Consume one message's frames.  Eager mode copies the payloads
        out before returning (shm record lifetime); deferred mode just
        references the zero-copy frames until :meth:`finish`."""
        if self._defer:
            self._msgs.append(frames)
            self.count += 1
            return
        from blendjax.native import copy_into

        np = self._np
        head = wire.loads(frames[0])
        payloads = frames[1:]
        i = self.count
        seen = set()
        for path, leaf, is_array in self._walk(head, payloads):
            if self._paths is not None and path not in self._paths:
                # generic collate keys the batch off its first item and
                # silently drops keys that only appear later — match it
                continue
            seen.add(path)
            if path in self._lists:
                self._lists[path].append(
                    np.array(leaf) if is_array else leaf
                )
                continue
            if is_array and i == 0:
                self._stacked[path] = self._batch_buffer(
                    path, leaf.shape, leaf.dtype
                )
            buf = self._stacked.get(path)
            if buf is not None and (
                leaf.shape == buf.shape[1:] and leaf.dtype == buf.dtype
            ):
                copy_into(buf[i], leaf)
                continue
            # shape/dtype drift (or a non-array leaf): degrade this key to
            # list mode, preserving earlier slots; the final collate then
            # applies the same ragged/upcast rules as the generic path.
            # Slots are COPIED out — a bare view would alias the (possibly
            # arena-backed, recycled) batch buffer and mutate after reuse
            prior = (
                [np.array(buf[j]) for j in range(i)]
                if buf is not None
                else self._lists.get(path, [])
            )
            self._stacked.pop(path, None)
            self._lists[path] = list(prior) + [
                np.array(leaf) if is_array else leaf
            ]
        if self._paths is None:
            self._paths = seen
        elif seen != self._paths:
            # a slot without a value for a first-message key would silently
            # misalign every later slot — fail loudly like dict collate
            missing = sorted(map(str, self._paths - seen))
            raise KeyError(
                f"stream message {i} of the current batch is missing "
                f"key(s) {missing} present in the batch's first message"
            )
        self.count += 1

    def finish(self):
        """Return the collated batch pytree (nested dict)."""
        if self._defer:
            return self._finish_deferred()
        from blendjax.btt.collate import collate as list_collate

        n = self.count
        out = {}
        for path, buf in self._stacked.items():
            _set_path(out, path, buf if n == self.batch_size else buf[:n])
        for path, vals in self._lists.items():
            _set_path(out, path, list_collate(vals) if vals else vals)
        return out

    def _finish_deferred(self):
        """Deferred columnar collation: parse the batch's headers in one
        pass, then collate column-by-column along the stream's cached
        plan — uniform array columns copy ONCE into the batch buffer (a
        GIL-released native ``gather_into`` for large frames, per-row
        assignment below the native threshold, where pointer extraction
        would cost more than the memcpy saves).  Any deviation from the
        plan falls back to the generic per-message walk (ragged,
        mixed-dtype, schema drift, compat containers) — the legacy
        collate rules, applied per key."""
        if not self._msgs:
            return {}
        loads = pickle.loads
        msgs = self._msgs
        heads = [loads(f[0]) for f in msgs]
        schema = self._schema_cache.get("schema")
        if schema is not None:
            out = self._columnar(heads, msgs, schema)
            if out is not None:
                return out
        return self._generic_deferred(heads, [f[1:] for f in msgs])


def _set_path(tree, path, value):
    node = tree
    for k in path[:-1]:
        node = node.setdefault(k, {})
    node[path[-1]] = value


class SingleFileDataset:
    """Map-style replay of one recording file."""

    def __init__(self, path, item_transform=None):
        self.reader = FileReader(path)
        self.item_transform = item_transform or _identity

    def __len__(self):
        return len(self.reader)

    def __getitem__(self, idx):
        return self._item(self.reader[idx])

    def _item(self, item):
        return self.item_transform(item)


class FileDataset:
    """Concatenated replay over all files matching ``{prefix}_*.btr``
    (reference ``dataset.py:134-153``), map-style so shuffling works."""

    def __init__(self, record_path_prefix, item_transform=None):
        fnames = sorted(glob(f"{record_path_prefix}_*.btr"))
        if not fnames:
            raise FileNotFoundError(
                f"Found no recording files with prefix {record_path_prefix}"
            )
        self.datasets = [SingleFileDataset(f) for f in fnames]
        self.cum_sizes = []
        total = 0
        for ds in self.datasets:
            total += len(ds)
            self.cum_sizes.append(total)
        self.item_transform = item_transform or _identity

    def __len__(self):
        return self.cum_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        if not 0 <= idx < len(self):
            raise IndexError(idx)
        ds_idx = bisect.bisect_right(self.cum_sizes, idx)
        start = 0 if ds_idx == 0 else self.cum_sizes[ds_idx - 1]
        return self._item(self.datasets[ds_idx][idx - start])

    def _item(self, item):
        return self.item_transform(item)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]
