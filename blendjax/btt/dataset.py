"""Streaming and replay datasets (reference ``btt/dataset.py:14-153``),
re-designed torch-free.

``RemoteIterableDataset`` pulls message dicts from N Blender producers over
a fan-in PULL socket (fair-queued across producers, HWM backpressure).  The
reference couples worker parallelism to ``torch.utils.data`` worker
processes; blendjax makes the split explicit — ``stream(worker_id,
num_workers, ...)`` — so any executor (threads in
:class:`blendjax.btt.loader.BatchLoader`, torch DataLoader workers via the
compat shim, or one stream per TPU host via ``shard``) can drive it.

Sharding semantics match the reference: each worker yields
``max_items // num_workers`` items (``dataset.py:97``), generalized to
``num_shards`` host-level shards for multi-host TPU slices (SURVEY.md §7
"multi-host sharding semantics").
"""

from __future__ import annotations

import bisect
import sys
import time
from contextlib import ExitStack
from glob import glob

import zmq

from blendjax import wire
from blendjax.btt.constants import DEFAULT_TIMEOUTMS
from blendjax.btt.file import FileReader, FileRecorder


def _identity(x):
    return x


def _torch_worker_info():
    """(worker_id, num_workers) when called inside a torch DataLoader worker.

    Import-free unless torch is already loaded: keeps the consumer package
    torch-independent while letting reference-style DataLoader use keep
    working.
    """
    utils_data = sys.modules.get("torch.utils.data")
    if utils_data is None:
        return None
    wi = utils_data.get_worker_info()
    if wi is None:
        return None
    return wi.id, wi.num_workers


class RemoteIterableDataset:
    """Iterable over message dicts streamed from remote Blender instances.

    Params
    ------
    addresses: list[str]
        Producer addresses to connect to (fan-in over all of them).
    queue_size: int
        RCVHWM; producers stall once this many messages are in flight.
    timeoutms: int
        Max silence before :class:`TimeoutError`.
    max_items: int
        Artificial dataset length (and recorder capacity).
    item_transform: callable | None
        Applied to each received dict.
    record_path_prefix: str | None
        When set, worker ``w`` records raw messages to
        ``{prefix}_{w:02d}.btr`` while streaming.
    """

    def __init__(
        self,
        addresses,
        queue_size=10,
        timeoutms=DEFAULT_TIMEOUTMS,
        max_items=100000,
        item_transform=None,
        record_path_prefix=None,
    ):
        self.addresses = list(addresses)
        self.queue_size = queue_size
        self.timeoutms = timeoutms
        self.max_items = max_items
        self.record_path_prefix = record_path_prefix
        self.item_transform = item_transform or _identity

    def enable_recording(self, fname):
        """Record while streaming; set before iteration starts."""
        self.record_path_prefix = fname

    def stream_length(self, max_items):
        """Set the artificial dataset length."""
        self.max_items = max_items

    def __iter__(self):
        wi = _torch_worker_info()
        if wi is not None:
            return self.stream(worker_id=wi[0], num_workers=wi[1])
        return self.stream()

    def stream(
        self,
        worker_id=0,
        num_workers=1,
        shard_id=0,
        num_shards=1,
        stop_event=None,
    ):
        """Generator yielding ``max_items // (num_workers * num_shards)``
        transformed items for this (shard, worker).

        ``stop_event`` (a ``threading.Event``) aborts the stream promptly —
        the poll loop checks it between messages so loaders can shut down
        without waiting out ``timeoutms``.

        ``shm://`` addresses take the native shared-memory path (see
        :mod:`blendjax.native.ring`): rings are single-consumer, so they are
        partitioned ``addresses[worker_id::num_workers]`` instead of the
        ZMQ connect-to-all fan-in; use ``num_workers <= len(addresses)``.
        """
        if self.addresses and all(a.startswith("shm://") for a in self.addresses):
            yield from self._stream_shm(
                worker_id, num_workers, shard_id, num_shards, stop_event
            )
            return
        ctx = zmq.Context.instance()
        socket = ctx.socket(zmq.PULL)
        socket.setsockopt(zmq.RCVHWM, self.queue_size)
        socket.setsockopt(zmq.LINGER, 0)
        try:
            for addr in self.addresses:
                socket.connect(addr)
            poller = zmq.Poller()
            poller.register(socket, zmq.POLLIN)

            count = self.max_items // (num_workers * num_shards)
            global_worker = shard_id * num_workers + worker_id
            with ExitStack() as es:
                rec = None
                if self.record_path_prefix is not None:
                    rec = es.enter_context(
                        FileRecorder(
                            FileRecorder.filename(
                                self.record_path_prefix, global_worker
                            ),
                            self.max_items,
                        )
                    )
                for _ in range(count):
                    waited = 0
                    slice_ms = 100 if stop_event is not None else self.timeoutms
                    while True:
                        if stop_event is not None and stop_event.is_set():
                            return
                        if poller.poll(min(slice_ms, self.timeoutms)):
                            break
                        waited += slice_ms
                        if waited >= self.timeoutms:
                            raise TimeoutError(
                                f"No message within {self.timeoutms} ms from "
                                f"{self.addresses}"
                            )
                    if rec is not None:
                        frames = wire.recv_message_raw(socket)
                        rec.save_frames(frames)
                        obj = wire.decode_raw_frames(frames)
                    else:
                        obj = wire.recv_message(socket)
                    yield self._item(obj)
        finally:
            socket.close(0)

    def _shm_rotation(self, worker_id, num_workers, stop_event, consume, count):
        """Shared ring-rotation loop for the shm paths: opens this worker's
        rings, round-robins ``consume(reader, block_ms)`` over them, and
        owns the EOF / timeout / stop semantics.  ``consume`` returns a
        result to yield, None when no message arrived in its slice, or
        raises EOFError when its ring is closed+drained (the ring then
        leaves the rotation; producer exit ends the stream instead of
        raising a timeout)."""
        from blendjax.native import ShmRingReader

        mine = self.addresses[worker_id::num_workers]
        if not mine:
            return
        # ring creation waits on producer startup: give it the stream timeout
        open_ms = max(self.timeoutms, 10000)
        readers = [ShmRingReader(a, open_timeout_ms=open_ms) for a in mine]
        try:
            delivered = 0
            waited_ms = 0
            # single ring (the common case: one worker per producer):
            # block inside the C call, 100 us wakeups.  Multi-ring:
            # non-blocking rotation with a short host-side sleep.
            block_ms = 100 if len(readers) == 1 else 0
            while delivered < count and readers:
                progressed = False
                for reader in list(readers):
                    if stop_event is not None and stop_event.is_set():
                        return
                    try:
                        res = consume(reader, block_ms)
                    except EOFError:
                        reader.close(unlink=True)  # drained + closed
                        readers.remove(reader)
                        block_ms = 100 if len(readers) == 1 else 0
                        continue
                    except ConnectionResetError:
                        # ring vanished and the producer isn't back within
                        # this slice; the reader stays retryable, so keep
                        # rotating until the dataset timeout expires (the
                        # watchdog respawn may land any moment)
                        waited_ms += max(block_ms, 0)
                        continue
                    if res is None:
                        waited_ms += max(block_ms, 0)
                        continue
                    progressed = True
                    waited_ms = 0
                    yield res
                    delivered += 1
                    if delivered >= count:
                        return
                if not progressed:
                    if block_ms == 0:
                        time.sleep(0.001)
                        waited_ms += 1
                    if waited_ms >= self.timeoutms:
                        raise TimeoutError(
                            f"No message within {self.timeoutms} ms from {mine}"
                        )
        finally:
            for r in readers:
                r.close()

    def _stream_shm(self, worker_id, num_workers, shard_id, num_shards, stop_event):
        """Native-transport variant of the stream loop (per-item)."""
        count = self.max_items // (num_workers * num_shards)
        with ExitStack() as es:
            rec = None
            if self.record_path_prefix is not None:
                rec = es.enter_context(
                    FileRecorder(
                        FileRecorder.filename(
                            self.record_path_prefix,
                            shard_id * num_workers + worker_id,
                        ),
                        self.max_items,
                    )
                )

            def consume(reader, block_ms):
                frames = reader.recv_frames(timeout_ms=block_ms)
                if frames is None:
                    return None
                if rec is not None:
                    rec.save_frames(frames)
                return (self._item(wire.decode(frames)),)

            for (item,) in self._shm_rotation(
                worker_id, num_workers, stop_event, consume, count
            ):
                yield item

    def _item(self, item):
        """Override point; defaults to ``item_transform`` (reference
        ``dataset.py:113-117``)."""
        return self.item_transform(item)

    # -- batched zero-intermediate-copy path (shm transport) ---------------

    def supports_batched_stream(self):
        """True when :meth:`stream_batches` can assemble batches straight
        out of the shm arena (native transport, no recording, no per-item
        transform)."""
        return (
            bool(self.addresses)
            and all(a.startswith("shm://") for a in self.addresses)
            and self.record_path_prefix is None
            and self.item_transform is _identity
            and type(self)._item is RemoteIterableDataset._item
        )

    def stream_batches(
        self,
        batch_size,
        worker_id=0,
        num_workers=1,
        shard_id=0,
        num_shards=1,
        stop_event=None,
        drop_last=True,
        timer=None,
    ):
        """Yield collated batches, bypassing per-item materialization.

        On the shm transport each message's array payloads normally cost
        two consumer-side copies: arena -> frame buffer
        (``recv_frames``), then frame buffers -> batch (``collate``).
        This path holds each ring record open just long enough to memcpy
        its payloads **directly into preallocated batch buffers**
        (``recv_frames_view`` + ``copy_into``, GIL released) — one copy,
        no intermediate allocations.

        Falls back to ``stream()`` + collate when
        :meth:`supports_batched_stream` is False.  Schema drift between
        messages (changed shape/dtype for a key) degrades that key to the
        generic collate rules instead of failing the stream.
        """
        from blendjax.btt.collate import collate as default_collate

        if timer is None:
            from blendjax.utils.timing import StageTimer

            timer = StageTimer()
        if not self.supports_batched_stream():
            batch = []
            for item in self.stream(
                worker_id=worker_id,
                num_workers=num_workers,
                shard_id=shard_id,
                num_shards=num_shards,
                stop_event=stop_event,
            ):
                batch.append(item)
                if len(batch) == batch_size:
                    with timer.stage("collate"):
                        out = default_collate(batch)
                    yield out
                    batch = []
            if batch and not drop_last:
                with timer.stage("collate"):
                    out = default_collate(batch)
                yield out
            return

        yield from self._stream_shm_batches(
            batch_size,
            worker_id,
            num_workers,
            shard_id,
            num_shards,
            stop_event,
            drop_last,
            timer,
        )

    def _stream_shm_batches(
        self,
        batch_size,
        worker_id,
        num_workers,
        shard_id,
        num_shards,
        stop_event,
        drop_last,
        timer,
    ):
        count = self.max_items // (num_workers * num_shards)
        state = {"builder": None}

        def consume(reader, block_ms):
            frames = reader.recv_frames_view(timeout_ms=block_ms)
            if frames is None:
                return None
            try:
                with timer.stage("collate"):
                    if state["builder"] is None:
                        state["builder"] = _BatchBuilder(batch_size)
                    state["builder"].add_message(frames)
            finally:
                reader.release_record()
            return True

        for _ in self._shm_rotation(
            worker_id, num_workers, stop_event, consume, count
        ):
            builder = state["builder"]
            if builder is not None and builder.full():
                yield builder.finish()
                state["builder"] = None
        builder = state["builder"]
        if builder is not None and builder.count and not drop_last:
            yield builder.finish()


class _BatchBuilder:
    """Assembles one collated batch directly from wire frames.

    Array leaves (raw-buffer placeholders or ndarrays in compat pickles)
    are memcpy'd into ``(batch_size, *shape)`` buffers preallocated on
    first sight of each key; everything else accumulates in per-key lists
    collated at the end.  Semantics mirror the generic
    ``stream() + collate`` path exactly: a key whose shape/dtype drifts
    mid-batch degrades to the ragged-list rules, keys absent from the
    batch's first message are dropped, and a message *missing* a
    first-message key raises KeyError (as dict collate would).
    """

    def __init__(self, batch_size):
        import numpy as np

        self._np = np
        self.batch_size = batch_size
        self.count = 0
        self._stacked = {}  # path -> preallocated (B, ...) ndarray
        self._lists = {}  # path -> list of leaves (generic collate at end)
        self._paths = None  # schema from the first message

    def full(self):
        return self.count >= self.batch_size

    # -- leaf walking -------------------------------------------------------

    def _view(self, placeholder, payloads):
        """ndarray view into the arena for a raw-buffer placeholder."""
        np = self._np
        return np.frombuffer(
            payloads[placeholder[wire.ARRAY_PLACEHOLDER]],
            dtype=np.dtype(placeholder["dtype"]),
        ).reshape(placeholder["shape"])

    def _resolve_copy(self, obj, payloads):
        """Deep-resolve placeholders inside a container to *owned* arrays
        (the arena views die when the record is released)."""
        np = self._np
        if wire.is_array_placeholder(obj):
            return np.array(self._view(obj, payloads))
        if isinstance(obj, dict):
            return {k: self._resolve_copy(v, payloads) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            seq = [self._resolve_copy(v, payloads) for v in obj]
            return seq if isinstance(obj, list) else tuple(seq)
        return obj

    def _walk(self, obj, payloads, path=()):
        """Yield (path, leaf, is_array) with raw-buffer placeholders
        resolved to ndarray views into the arena.  list/tuple containers
        are resolved to owned copies and treated as single leaves — the
        final ``collate`` recurses into them exactly like the generic
        path does."""
        np = self._np
        if isinstance(obj, dict):
            if wire.is_array_placeholder(obj):
                yield path, self._view(obj, payloads), True
                return
            for k, v in obj.items():
                yield from self._walk(v, payloads, path + (k,))
            return
        if isinstance(obj, np.ndarray):
            yield path, obj, True
            return
        if isinstance(obj, (list, tuple)):
            yield path, self._resolve_copy(obj, payloads), False
            return
        yield path, obj, False

    def add_message(self, frames):
        """Consume one message's frames (views valid only for this call)."""
        from blendjax.native import copy_into

        np = self._np
        head = wire.loads(frames[0])
        payloads = frames[1:]
        i = self.count
        seen = set()
        for path, leaf, is_array in self._walk(head, payloads):
            if self._paths is not None and path not in self._paths:
                # generic collate keys the batch off its first item and
                # silently drops keys that only appear later — match it
                continue
            seen.add(path)
            if path in self._lists:
                self._lists[path].append(
                    np.array(leaf) if is_array else leaf
                )
                continue
            if is_array and i == 0:
                self._stacked[path] = np.empty(
                    (self.batch_size,) + leaf.shape, leaf.dtype
                )
            buf = self._stacked.get(path)
            if buf is not None and (
                leaf.shape == buf.shape[1:] and leaf.dtype == buf.dtype
            ):
                copy_into(buf[i], leaf)
                continue
            # shape/dtype drift (or a non-array leaf): degrade this key to
            # list mode, preserving earlier slots; the final collate then
            # applies the same ragged/upcast rules as the generic path
            prior = (
                [buf[j] for j in range(i)]
                if buf is not None
                else self._lists.get(path, [])
            )
            self._stacked.pop(path, None)
            self._lists[path] = list(prior) + [
                np.array(leaf) if is_array else leaf
            ]
        if self._paths is None:
            self._paths = seen
        elif seen != self._paths:
            # a slot without a value for a first-message key would silently
            # misalign every later slot — fail loudly like dict collate
            missing = sorted(map(str, self._paths - seen))
            raise KeyError(
                f"stream message {i} of the current batch is missing "
                f"key(s) {missing} present in the batch's first message"
            )
        self.count += 1

    def finish(self):
        """Return the collated batch pytree (nested dict)."""
        from blendjax.btt.collate import collate as list_collate

        n = self.count
        out = {}
        for path, buf in self._stacked.items():
            _set_path(out, path, buf if n == self.batch_size else buf[:n])
        for path, vals in self._lists.items():
            _set_path(out, path, list_collate(vals) if vals else vals)
        return out


def _set_path(tree, path, value):
    node = tree
    for k in path[:-1]:
        node = node.setdefault(k, {})
    node[path[-1]] = value


class SingleFileDataset:
    """Map-style replay of one recording file."""

    def __init__(self, path, item_transform=None):
        self.reader = FileReader(path)
        self.item_transform = item_transform or _identity

    def __len__(self):
        return len(self.reader)

    def __getitem__(self, idx):
        return self._item(self.reader[idx])

    def _item(self, item):
        return self.item_transform(item)


class FileDataset:
    """Concatenated replay over all files matching ``{prefix}_*.btr``
    (reference ``dataset.py:134-153``), map-style so shuffling works."""

    def __init__(self, record_path_prefix, item_transform=None):
        fnames = sorted(glob(f"{record_path_prefix}_*.btr"))
        if not fnames:
            raise FileNotFoundError(
                f"Found no recording files with prefix {record_path_prefix}"
            )
        self.datasets = [SingleFileDataset(f) for f in fnames]
        self.cum_sizes = []
        total = 0
        for ds in self.datasets:
            total += len(ds)
            self.cum_sizes.append(total)
        self.item_transform = item_transform or _identity

    def __len__(self):
        return self.cum_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        if not 0 <= idx < len(self):
            raise IndexError(idx)
        ds_idx = bisect.bisect_right(self.cum_sizes, idx)
        start = 0 if ds_idx == 0 else self.cum_sizes[ds_idx - 1]
        return self._item(self.datasets[ds_idx][idx - start])

    def _item(self, item):
        return self.item_transform(item)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]
