"""Streaming and replay datasets (reference ``btt/dataset.py:14-153``),
re-designed torch-free.

``RemoteIterableDataset`` pulls message dicts from N Blender producers over
a fan-in PULL socket (fair-queued across producers, HWM backpressure).  The
reference couples worker parallelism to ``torch.utils.data`` worker
processes; blendjax makes the split explicit — ``stream(worker_id,
num_workers, ...)`` — so any executor (threads in
:class:`blendjax.btt.loader.BatchLoader`, torch DataLoader workers via the
compat shim, or one stream per TPU host via ``shard``) can drive it.

Sharding semantics match the reference: each worker yields
``max_items // num_workers`` items (``dataset.py:97``), generalized to
``num_shards`` host-level shards for multi-host TPU slices (SURVEY.md §7
"multi-host sharding semantics").
"""

from __future__ import annotations

import bisect
import sys
import time
from contextlib import ExitStack
from glob import glob

import zmq

from blendjax import wire
from blendjax.btt.constants import DEFAULT_TIMEOUTMS
from blendjax.btt.file import FileReader, FileRecorder


def _identity(x):
    return x


def _torch_worker_info():
    """(worker_id, num_workers) when called inside a torch DataLoader worker.

    Import-free unless torch is already loaded: keeps the consumer package
    torch-independent while letting reference-style DataLoader use keep
    working.
    """
    utils_data = sys.modules.get("torch.utils.data")
    if utils_data is None:
        return None
    wi = utils_data.get_worker_info()
    if wi is None:
        return None
    return wi.id, wi.num_workers


class RemoteIterableDataset:
    """Iterable over message dicts streamed from remote Blender instances.

    Params
    ------
    addresses: list[str]
        Producer addresses to connect to (fan-in over all of them).
    queue_size: int
        RCVHWM; producers stall once this many messages are in flight.
    timeoutms: int
        Max silence before :class:`TimeoutError`.
    max_items: int
        Artificial dataset length (and recorder capacity).
    item_transform: callable | None
        Applied to each received dict.
    record_path_prefix: str | None
        When set, worker ``w`` records raw messages to
        ``{prefix}_{w:02d}.btr`` while streaming.
    """

    def __init__(
        self,
        addresses,
        queue_size=10,
        timeoutms=DEFAULT_TIMEOUTMS,
        max_items=100000,
        item_transform=None,
        record_path_prefix=None,
    ):
        self.addresses = list(addresses)
        self.queue_size = queue_size
        self.timeoutms = timeoutms
        self.max_items = max_items
        self.record_path_prefix = record_path_prefix
        self.item_transform = item_transform or _identity

    def enable_recording(self, fname):
        """Record while streaming; set before iteration starts."""
        self.record_path_prefix = fname

    def stream_length(self, max_items):
        """Set the artificial dataset length."""
        self.max_items = max_items

    def __iter__(self):
        wi = _torch_worker_info()
        if wi is not None:
            return self.stream(worker_id=wi[0], num_workers=wi[1])
        return self.stream()

    def stream(
        self,
        worker_id=0,
        num_workers=1,
        shard_id=0,
        num_shards=1,
        stop_event=None,
    ):
        """Generator yielding ``max_items // (num_workers * num_shards)``
        transformed items for this (shard, worker).

        ``stop_event`` (a ``threading.Event``) aborts the stream promptly —
        the poll loop checks it between messages so loaders can shut down
        without waiting out ``timeoutms``.

        ``shm://`` addresses take the native shared-memory path (see
        :mod:`blendjax.native.ring`): rings are single-consumer, so they are
        partitioned ``addresses[worker_id::num_workers]`` instead of the
        ZMQ connect-to-all fan-in; use ``num_workers <= len(addresses)``.
        """
        if self.addresses and all(a.startswith("shm://") for a in self.addresses):
            yield from self._stream_shm(
                worker_id, num_workers, shard_id, num_shards, stop_event
            )
            return
        ctx = zmq.Context.instance()
        socket = ctx.socket(zmq.PULL)
        socket.setsockopt(zmq.RCVHWM, self.queue_size)
        socket.setsockopt(zmq.LINGER, 0)
        try:
            for addr in self.addresses:
                socket.connect(addr)
            poller = zmq.Poller()
            poller.register(socket, zmq.POLLIN)

            count = self.max_items // (num_workers * num_shards)
            global_worker = shard_id * num_workers + worker_id
            with ExitStack() as es:
                rec = None
                if self.record_path_prefix is not None:
                    rec = es.enter_context(
                        FileRecorder(
                            FileRecorder.filename(
                                self.record_path_prefix, global_worker
                            ),
                            self.max_items,
                        )
                    )
                for _ in range(count):
                    waited = 0
                    slice_ms = 100 if stop_event is not None else self.timeoutms
                    while True:
                        if stop_event is not None and stop_event.is_set():
                            return
                        if poller.poll(min(slice_ms, self.timeoutms)):
                            break
                        waited += slice_ms
                        if waited >= self.timeoutms:
                            raise TimeoutError(
                                f"No message within {self.timeoutms} ms from "
                                f"{self.addresses}"
                            )
                    if rec is not None:
                        frames = wire.recv_message_raw(socket)
                        rec.save_frames(frames)
                        obj = wire.decode_raw_frames(frames)
                    else:
                        obj = wire.recv_message(socket)
                    yield self._item(obj)
        finally:
            socket.close(0)

    def _stream_shm(self, worker_id, num_workers, shard_id, num_shards, stop_event):
        """Native-transport variant of the stream loop: round-robin over
        this worker's rings; a closed+drained ring leaves the rotation
        (producer exit ends the stream instead of raising a timeout)."""
        from blendjax.native import ShmRingReader

        mine = self.addresses[worker_id::num_workers]
        if not mine:
            return
        # ring creation waits on producer startup: give it the stream timeout
        open_ms = max(self.timeoutms, 10000)
        readers = [ShmRingReader(a, open_timeout_ms=open_ms) for a in mine]
        count = self.max_items // (num_workers * num_shards)
        try:
            with ExitStack() as es:
                rec = None
                if self.record_path_prefix is not None:
                    rec = es.enter_context(
                        FileRecorder(
                            FileRecorder.filename(
                                self.record_path_prefix,
                                shard_id * num_workers + worker_id,
                            ),
                            self.max_items,
                        )
                    )
                delivered = 0
                waited_ms = 0
                # single ring (the common case: one worker per producer):
                # block inside the C call, 100 us wakeups.  Multi-ring:
                # non-blocking rotation with a short host-side sleep.
                block_ms = 100 if len(readers) == 1 else 0
                while delivered < count and readers:
                    progressed = False
                    for reader in list(readers):
                        if stop_event is not None and stop_event.is_set():
                            return
                        try:
                            frames = reader.recv_frames(timeout_ms=block_ms)
                        except EOFError:
                            reader.close(unlink=True)  # drained + closed
                            readers.remove(reader)
                            block_ms = 100 if len(readers) == 1 else 0
                            continue
                        if frames is None:
                            waited_ms += max(block_ms, 0)
                            continue
                        progressed = True
                        waited_ms = 0
                        if rec is not None:
                            rec.save_frames(frames)
                        yield self._item(wire.decode(frames))
                        delivered += 1
                        if delivered >= count:
                            return
                    if not progressed:
                        if block_ms == 0:
                            time.sleep(0.001)
                            waited_ms += 1
                        if waited_ms >= self.timeoutms:
                            raise TimeoutError(
                                f"No message within {self.timeoutms} ms from {mine}"
                            )
        finally:
            for r in readers:
                r.close()

    def _item(self, item):
        """Override point; defaults to ``item_transform`` (reference
        ``dataset.py:113-117``)."""
        return self.item_transform(item)


class SingleFileDataset:
    """Map-style replay of one recording file."""

    def __init__(self, path, item_transform=None):
        self.reader = FileReader(path)
        self.item_transform = item_transform or _identity

    def __len__(self):
        return len(self.reader)

    def __getitem__(self, idx):
        return self._item(self.reader[idx])

    def _item(self, item):
        return self.item_transform(item)


class FileDataset:
    """Concatenated replay over all files matching ``{prefix}_*.btr``
    (reference ``dataset.py:134-153``), map-style so shuffling works."""

    def __init__(self, record_path_prefix, item_transform=None):
        fnames = sorted(glob(f"{record_path_prefix}_*.btr"))
        if not fnames:
            raise FileNotFoundError(
                f"Found no recording files with prefix {record_path_prefix}"
            )
        self.datasets = [SingleFileDataset(f) for f in fnames]
        self.cum_sizes = []
        total = 0
        for ds in self.datasets:
            total += len(ds)
            self.cum_sizes.append(total)
        self.item_transform = item_transform or _identity

    def __len__(self):
        return self.cum_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        if not 0 <= idx < len(self):
            raise IndexError(idx)
        ds_idx = bisect.bisect_right(self.cum_sizes, idx)
        start = 0 if ds_idx == 0 else self.cum_sizes[ds_idx - 1]
        return self._item(self.datasets[ds_idx][idx - start])

    def _item(self, item):
        return self.item_transform(item)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]
