"""Device feed: double-buffered host->HBM prefetch.

This module is the TPU-native seam the whole framework exists for
(BASELINE.json north star): batches coming off the ZMQ stream are staged
into device memory *while the previous train step runs*, so the TPU never
waits on the host.  ``jax.device_put`` dispatches asynchronously; keeping
``size`` batches in flight from a background thread overlaps H2D DMA with
XLA compute — the reference's equivalent path is torch DataLoader +
``.to(device)`` inside the train loop, which serializes transfer and step.

Multi-device feeds pass a ``jax.sharding.Sharding`` (e.g. batch split over
the mesh's 'data' axis); on multi-host slices each process feeds its local
shard and ``make_array_from_process_local_data`` assembles the global array.
"""

from __future__ import annotations

import contextlib
import logging
import os
import queue
import threading
import time

import jax
import numpy as np

from blendjax.utils.timing import StageTimer

log = logging.getLogger("blendjax")

_SENTINEL = object()


class TransferGate:
    """Pauses feed workers while a host->device transfer is in flight.

    On core-starved hosts (TPU-VM sidecars, CI containers) the tunnel/PCIe
    client that pumps ``device_put`` shares its core with the collate and
    recv threads; any concurrently running Python thread then stretches the
    transfer by GIL-handoff latency (measured on a 1-core host: 9.8 MB
    batch 5.5 ms alone vs 33.8 ms with one numpy thread running — ~6x).
    Serializing the two is strictly cheaper there: the gate closes for the
    duration of each transfer and feed workers block at their next batch
    boundary instead of stealing the core.

    The gate refcounts in-flight transfers (a ``Condition`` over a
    counter, not a bare ``Event``), so one gate can safely be shared
    across several streams: it opens only when EVERY transfer holding it
    has finished — with an event, the first transfer to finish would
    reopen the gate while a second was still in flight.

    On hosts with cores to spare the gate stays open permanently
    (``JaxStream(transfer_gate='auto')``) and costs one check per batch.

    Params
    ------
    timeout: float
        Liveness backstop for :meth:`wait` — a crashed transfer thread
        must not freeze the feed forever.  When it fires, a warning is
        logged once per stall episode (re-armed each time the gate next
        opens, so a later unrelated stall — e.g. after a relay recovery —
        is visible too; ADVICE r4) and the ``transfer_gate_backstops``
        fleet counter increments (every fire: the counter is the
        quantitative record, the log is the narrative one).
    counters: EventCounters | None
        Backstop-fire sink; defaults to the process-wide
        ``blendjax.utils.timing.fleet_counters`` so
        ``FleetSupervisor.health()`` sees the fires.
    """

    def __init__(self, timeout=5.0, counters=None):
        from blendjax.utils.timing import fleet_counters

        self._cond = threading.Condition()
        self._inflight = 0
        self.timeout = timeout
        self._warned = False
        self._counters = counters if counters is not None else fleet_counters

    def wait(self, timeout=None, stop=None):
        """Feed-worker side: block while any transfer is in flight.

        Returns ``True`` when the gate actually opened, ``False`` when
        the wait ended for another reason — ``stop`` (an optional
        ``threading.Event``) was set, so a closing loader never sits out
        the full backstop, or the liveness backstop expired."""
        deadline = time.monotonic() + (
            self.timeout if timeout is None else timeout
        )
        with self._cond:
            while self._inflight > 0:
                if stop is not None and stop.is_set():
                    return False
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._counters.incr("transfer_gate_backstops")
                    if not self._warned:
                        self._warned = True
                        log.warning(
                            "TransferGate backstop fired after %.1fs: a "
                            "transfer is outliving the gate timeout "
                            "(crashed pump, or raise TransferGate("
                            "timeout=...))", self.timeout,
                        )
                    return False
                self._cond.wait(min(0.1, remaining))
        return True

    @contextlib.contextmanager
    def transfer(self):
        """Transfer side: hold the gate closed for the duration of the
        block.  Re-entrant across threads: the gate opens when the LAST
        concurrent transfer exits."""
        with self._cond:
            self._inflight += 1
        try:
            yield
        finally:
            with self._cond:
                self._inflight -= 1
                if self._inflight <= 0:
                    # gate opens: re-arm the backstop warning so the next
                    # stall episode logs again
                    self._warned = False
                    self._cond.notify_all()


def _resolve_gate(transfer_gate, num_workers):
    """'auto' enables the gate only where serializing wins: a non-cpu
    backend (there is a real transfer engine to protect) on a host whose
    cores are outnumbered by feed threads + the transfer pump."""
    if transfer_gate == "auto":
        cores = os.cpu_count() or 1
        if cores <= num_workers + 1 and jax.default_backend() != "cpu":
            return TransferGate()
        return None
    if transfer_gate is True:
        return TransferGate()
    if transfer_gate in (False, None):
        return None
    if isinstance(transfer_gate, TransferGate):
        return transfer_gate  # caller-supplied gate (shared across streams)
    raise ValueError(
        f"transfer_gate must be 'auto', a bool, None, or a TransferGate; "
        f"got {transfer_gate!r}"
    )


def _resolve_arena(arena, dataset, collate_fn, num_workers, prefetch):
    """Resolve JaxStream's ``arena`` option to an ArenaPool (or None).

    'auto' (the default) enables arena-pooled batch assembly whenever
    the dataset supports the batched stream path and the default collate
    is in use — i.e. fixed-shape raw-buffer streams get recycled batch
    buffers out of the box, with the legacy collate fallback applying
    per key for ragged/compat traffic.  Pool depth covers every place a
    batch can be in flight at once (loader queue + device queue + one in
    transfer + one building per worker).
    """
    from blendjax.btt.arena import ArenaPool

    # identity checks: `0 in (False, None)` is True, and arena=0 must hit
    # ArenaPool's pool_size validation below, not silently disable
    if arena is False or arena is None:
        return None
    if isinstance(arena, ArenaPool):
        return arena
    supported = (
        collate_fn is None
        and hasattr(dataset, "supports_batched_stream")
        and dataset.supports_batched_stream()
    )
    if arena == "auto":
        if not supported:
            return None
        return ArenaPool(pool_size=num_workers + prefetch + 3)
    if arena is True:
        if not supported:
            raise ValueError(
                "arena=True requires a dataset whose batched stream path "
                "is available (no recording/per-item transform) and the "
                "default collate"
            )
        return ArenaPool(pool_size=num_workers + prefetch + 3)
    if isinstance(arena, int):
        return ArenaPool(pool_size=arena)
    raise ValueError(
        f"arena must be 'auto', a bool, None, an int pool size, or an "
        f"ArenaPool; got {arena!r}"
    )


def own_arena_leaves(host_batch, arena):
    """Host-copy the leaves of ``host_batch`` still backed by ``arena``
    memory, returning a pytree safe to hold past the arena's recycle.

    On the CPU backend ``jax.device_put`` zero-copies aligned numpy
    arrays (``may_alias=False`` included): the resulting ``jax.Array``
    ALIASES the arena buffer, so recycling the arena would let the next
    batch's scatter mutate an already-transferred "device" batch in
    place.  Leaves a copying transform already detached are passed
    through untouched; real accelerators never need this — their H2D DMA
    is the copy, fenced by ``block_until_ready`` before recycle.  Shared
    by :func:`device_prefetch` and the podracer fan-in
    (:meth:`blendjax.parallel.podracer.SegmentFanIn.to_device`)."""
    bufs = tuple(arena.buffers.values())

    def _own(x):
        arr = np.asarray(x)
        if any(np.may_share_memory(arr, b) for b in bufs):
            return np.array(arr)
        return x

    return jax.tree.map(_own, host_batch)


def put_batch(batch, sharding=None):
    """Place one host batch (numpy pytree) onto device(s).

    With no ``sharding``: default device.  With a sharding on a single-host
    mesh: ``device_put`` shards directly.  On multi-host meshes the local
    batch is treated as this process's shard of the global batch.
    """
    if sharding is None:
        return jax.device_put(batch)
    if jax.process_count() > 1:
        # local arrays are SHARDS of the global batch here — validating
        # them against the global sharding spec would spuriously reject
        # valid feeds; make_array_from_process_local_data does its own
        # global-shape reconstruction and validation
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(
                sharding, np.asarray(x)
            ),
            batch,
        )
    leaf = next(iter(jax.tree.leaves(batch)), None)
    if leaf is not None and hasattr(leaf, "shape"):
        # shard_shape validates per-DIMENSION divisibility against the
        # sharding's partition spec — the old total-device-count check
        # wrongly rejected multi-axis shardings (e.g. P('data','seq')
        # over an 8-device mesh only needs batch % data_axis == 0)
        try:
            sharding.shard_shape(tuple(leaf.shape))
        except Exception as e:
            raise ValueError(
                f"batch of shape {tuple(leaf.shape)} not shardable as "
                f"{sharding}: {e}; pick batch/sequence sizes divisible "
                "by the mesh axes they shard over"
            ) from e
    return jax.device_put(batch, sharding)


def device_prefetch(iterator, size=2, sharding=None, transform=None, timer=None,
                    gate=None):
    """Wrap ``iterator`` (host batches) into an iterator of device batches.

    Params
    ------
    iterator: iterable of numpy pytrees
    size: int
        Batches kept in flight (2 = classic double buffering).
    sharding: jax.sharding.Sharding | None
        Placement for every leaf (leading-axis batch sharding for DP).
    transform: callable | None
        Host-side pre-transfer hook (key selection, dtype cast, layout).
    timer: StageTimer | None
        Records ``device_put`` stage times.
    gate: TransferGate | None
        When set, the gate is held closed for each transfer (including its
        completion, so the pump owns the core end to end) — see
        :class:`TransferGate`.
    """
    if size < 1:
        raise ValueError("prefetch size must be >= 1")
    timer = timer or StageTimer()
    q: queue.Queue = queue.Queue(maxsize=size)
    stop = threading.Event()

    def _producer():
        from blendjax.btt.arena import ArenaBatch

        batch = None
        try:
            for batch in iterator:
                if stop.is_set():
                    if isinstance(batch, ArenaBatch):
                        batch.recycle()
                    return
                host_batch = (
                    batch.data if isinstance(batch, ArenaBatch) else batch
                )
                if transform is not None:
                    host_batch = transform(host_batch)
                if isinstance(batch, ArenaBatch) and \
                        jax.default_backend() == "cpu":
                    # see own_arena_leaves: CPU device_put aliases arena
                    # memory, so detach before the recycle below
                    host_batch = own_arena_leaves(host_batch, batch.arena)
                with timer.stage("device_put"):
                    if gate is not None:
                        with gate.transfer():
                            dev_batch = put_batch(host_batch, sharding)
                            # the gate must stay closed until the bytes have
                            # actually landed, not just been dispatched
                            jax.block_until_ready(dev_batch)
                    else:
                        dev_batch = put_batch(host_batch, sharding)
                if isinstance(batch, ArenaBatch):
                    # the arena returns to the freelist only once the
                    # transfer has COMPLETED (dispatch alone still reads
                    # host memory); a slow trainer therefore backpressures
                    # into the pool instead of allocating unboundedly.
                    # The gated path already blocked above.
                    if gate is None:
                        jax.block_until_ready(dev_batch)
                    with timer.stage("recycle"):
                        batch.recycle()
                while True:
                    try:
                        q.put(dev_batch, timeout=0.5)
                        break
                    except queue.Full:
                        if stop.is_set():
                            return
            q.put(_SENTINEL)
        except BaseException as exc:  # noqa: BLE001 - forwarded to consumer
            # a transform/put failure must not strand the in-hand arena
            # (recycle is idempotent, so an already-recycled batch is safe)
            if isinstance(batch, ArenaBatch):
                batch.recycle()
            q.put(exc)

    thread = threading.Thread(target=_producer, daemon=True, name="bjx-prefetch")
    thread.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        thread.join(timeout=5)


class JaxStream:
    """End-to-end feed: remote stream -> batches -> device, with timing.

    The one-stop replacement for the reference's
    ``DataLoader(RemoteIterableDataset(...))`` pattern::

        ds = btt.RemoteIterableDataset(addresses, max_items=...)
        stream = btt.JaxStream(ds, batch_size=8, num_workers=4,
                               sharding=data_sharding(mesh))
        for batch in stream:          # jax.Arrays already in HBM
            state, loss = train_step(state, batch)

    ``stream.timer.summary()`` exposes the per-stage feed times (recv /
    scatter / arena_wait / device_put / recycle on the arena path,
    recv / collate / device_put on the legacy path);
    ``stream.duty_cycle(...)`` measures the feed's headroom.

    ``arena='auto'`` (default) assembles batches into recycled
    arena-pooled buffers (:mod:`blendjax.btt.arena`) whenever the
    dataset supports the batched stream path: one host copy from wire
    frame to batch slot, arenas recycled only after each device
    transfer completes (pool exhaustion = backpressure).  Pass False to
    force the legacy per-batch allocation, an int to size the pool, or
    a shared ``ArenaPool``.
    """

    def __init__(
        self,
        dataset,
        batch_size,
        num_workers=1,
        sharding=None,
        transform=None,
        prefetch=2,
        shard=(0, 1),
        drop_last=True,
        collate_fn=None,
        timer=None,
        transfer_gate="auto",
        arena="auto",
    ):
        from blendjax.btt.loader import BatchLoader

        self.gate = _resolve_gate(transfer_gate, num_workers)
        self.arena_pool = _resolve_arena(
            arena, dataset, collate_fn, num_workers, prefetch
        )
        self.loader = BatchLoader(
            dataset,
            batch_size,
            num_workers=num_workers,
            shard=shard,
            drop_last=drop_last,
            collate_fn=collate_fn,
            timer=timer,
            gate=self.gate,
            arena_pool=self.arena_pool,
        )
        self.sharding = sharding
        self.transform = transform
        self.prefetch = prefetch
        self.timer = self.loader.timer

    def __len__(self):
        return len(self.loader)

    def duty_cycle(self, name):
        """Fraction of wall time (since the timer's last reset) spent in
        stage ``name`` — e.g. ``duty_cycle('device_put')`` for the feed's
        transfer share, or a caller-recorded ``'step'`` stage for train
        duty cycle.  Delegates to :meth:`StageTimer.duty_cycle`."""
        return self.timer.duty_cycle(name)

    def __iter__(self):
        return device_prefetch(
            iter(self.loader),
            size=self.prefetch,
            sharding=self.sharding,
            transform=self.transform,
            timer=self.timer,
            gate=self.gate,
        )

    def close(self):
        self.loader.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
