"""Spawn and supervise Blender producer fleets (reference ``btt/launcher.py:15-197``).

``BlenderLauncher`` is a context manager that starts ``num_instances``
Blender processes, each running a user script with the framework arg
protocol (``-btid/-btseed/-btsockets`` after Blender's ``--`` separator) and
one pre-allocated address per named socket per instance.  On TPU pods, one
launcher runs per host; combined with ``bind_addr='primaryip'`` and the
``LaunchInfo`` JSON handoff this fans fleets out across every TPU-VM host of
a slice (SURVEY.md §2.4).

Differences from the reference, on purpose:
- the POSIX/Windows process-group kwargs are actually passed to ``Popen``
  (reference computes them into a dead variable, ``launcher.py:124-132``);
- shutdown escalates terminate -> kill on the whole process group with a
  timeout instead of hanging forever on a wedged child;
- launch failures raise ``RuntimeError`` rather than tripping asserts.
"""

from __future__ import annotations

import logging
import os
import signal as _signal
import subprocess

import numpy as np

from blendjax.btt.finder import discover_blender
from blendjax.btt.launch_info import LaunchInfo
from blendjax.btt.utils import get_primary_ip

logger = logging.getLogger("blendjax")


def popen_group_kwargs():
    """Popen kwargs isolating the child in its own process group, so fleet
    teardown can signal whole process trees without touching the caller's
    group (fixes the reference's dead-variable bug, ``launcher.py:124-132``,
    and is shared with the watchdog's respawn path)."""
    if os.name == "posix":
        return {"preexec_fn": os.setsid}
    if os.name == "nt":
        return {"creationflags": subprocess.CREATE_NEW_PROCESS_GROUP}
    return {}


def child_env():
    """Environment for producer subprocesses.

    ``--python-use-system-env`` tells Blender to honor PYTHONPATH; prepend the
    package root that provides ``blendjax`` (the btb producer side) so
    producer scripts can import it even when the launching process found it
    via cwd alone.  Shared with the watchdog's respawn path.
    """
    env = os.environ.copy()
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (pkg_root, env.get("PYTHONPATH", "")) if p
    )
    return env


class BlenderLauncher:
    """Context manager launching and tearing down Blender instances.

    Params
    ------
    scene: str
        ``.blend`` scene file each instance opens ('' or None for none).
    script: str
        Python script Blender runs (the producer-side ``*.blend.py``).
    num_instances: int
        Number of Blender processes to spawn.
    named_sockets: list[str]
        Socket names to pre-allocate addresses for; passed to instances as
        ``-btsockets NAME=ADDR ...`` and exposed via ``launch_info``.
    start_port: int
        First port of the allocated range (one port per socket per instance).
    bind_addr: str
        Bind address for producer sockets; ``'primaryip'`` resolves the
        default-route interface so other hosts can connect.
    instance_args: list[list[str]] | None
        Extra per-instance CLI args appended after the framework args.
    proto: str
        Transport: ``'tcp'`` (default), ``'ipc'``, or ``'shm'`` (native
        same-host shared-memory rings, see :mod:`blendjax.native.ring`).
    blend_path: str | None
        Extra PATH entries searched for the Blender executable.
    seed: int | None
        Base seed; instance ``i`` receives ``seed + i`` so domain
        randomization decorrelates across the fleet.
    background: bool
        Pass ``--background`` (headless; note Eevee offscreen rendering
        needs a GL context — use a virtual display wrapper via
        ``$BLENDJAX_BLENDER`` on headless hosts).
    shutdown_grace: float
        Seconds to wait after terminate before killing the process group.
    """

    def __init__(
        self,
        scene,
        script,
        num_instances=1,
        named_sockets=None,
        start_port=11000,
        bind_addr="127.0.0.1",
        instance_args=None,
        proto="tcp",
        blend_path=None,
        seed=None,
        background=False,
        shutdown_grace=5.0,
    ):
        if num_instances <= 0:
            raise ValueError("num_instances must be positive")
        self.scene = scene
        self.script = script
        self.num_instances = num_instances
        self.named_sockets = list(named_sockets or [])
        self.start_port = start_port
        self.bind_addr = bind_addr
        self.proto = proto
        self.blend_path = blend_path
        self.seed = seed
        self.background = background
        self.shutdown_grace = shutdown_grace
        self.instance_args = (
            [list(a) for a in instance_args]
            if instance_args is not None
            else [[] for _ in range(num_instances)]
        )
        if len(self.instance_args) != num_instances:
            raise ValueError(
                f"instance_args has {len(self.instance_args)} entries "
                f"for {num_instances} instances"
            )

        # 8 hex chars of urandom: unique per launch, shared by respawns
        self._nonce = os.urandom(4).hex()
        #: per-launch /dev/shm base PREFIX (PR-12 ShmRPC hygiene
        #: discipline): every shm object this launch creates — rings
        #: and any side objects the ring layer names under them — sits
        #: under one glob-able prefix, so teardown is one sweep instead
        #: of per-address unlinks that miss what a SIGKILLed producer
        #: half-created
        self._shm_base = f"blendjax-{self._nonce}"

        self.blender_info = discover_blender(self.blend_path)
        if self.blender_info is None:
            raise RuntimeError(
                "Blender not found or misconfigured (set $BLENDJAX_BLENDER "
                "or install producer requirements into Blender's Python)."
            )
        logger.info(
            "Blender found at %s version %d.%d",
            self.blender_info["path"],
            self.blender_info["major"],
            self.blender_info["minor"],
        )
        self.launch_info = None

    # -- address allocation -------------------------------------------------

    def _addresses(self):
        """One address per (socket name, instance), ports ascending.

        shm names live under the per-launch nonce'd base prefix
        (``self._shm_base``): addresses travel to producers via
        ``-btsockets``, so no deterministic rendezvous is needed, and a
        ring leaked by a previous run (SIGKILL teardown) can never be
        mistaken for this launch's ring — the stale-generation poisoning
        found in round 2 (VERDICT r2 weak #2).  Watchdog respawns reuse
        the original command line, hence the same nonce'd name, so the
        reader's generation-reopen elasticity still works; teardown
        sweeps the whole prefix in one glob (see :meth:`_unlink_shm`).
        """
        bind = self.bind_addr
        if bind == "primaryip":
            bind = get_primary_ip()
        addresses, port = {}, self.start_port
        for name in self.named_sockets:
            addrs = []
            for idx in range(self.num_instances):
                if self.proto == "ipc":
                    addrs.append(f"ipc:///tmp/blendjax-{name}-{port + idx}.ipc")
                elif self.proto == "shm":
                    addrs.append(
                        f"shm://{self._shm_base}-{name}-{port + idx}"
                    )
                else:
                    addrs.append(f"{self.proto}://{bind}:{port + idx}")
            port += self.num_instances
            addresses[name] = addrs
        return addresses

    def _unlink_shm(self, addresses=None):
        """Remove EVERY shm object under this launch's base prefix
        (teardown hygiene: a SIGKILLed producer never runs its unlink
        path; without this every crash strands capacity_bytes in
        /dev/shm).  One ``unlink_base`` glob sweep — the PR-12 ShmRPC
        discipline — instead of per-address unlinks, so side objects
        named under a ring's prefix (bells, a half-created segment of
        a crashed spawn) go with it.  The nonce'd base makes the glob
        collision-proof against other launches."""
        if self.proto != "shm":
            return
        from blendjax.btt.shm_rpc import unlink_base

        removed = unlink_base(self._shm_base)
        if removed:
            logger.debug("swept %d shm objects under %s",
                         len(removed), self._shm_base)

    def _unlink_instance_shm(self, idx):
        """Sweep ONE instance's shm objects (its rings and any side
        objects named under their prefixes) — the per-instance half of
        the ``unlink_base`` hygiene, for the paths where one process is
        replaced or removed while the launch lives on.  A SIGKILLed
        producer never runs its own unlink; a live reader of a swept
        ring sees the vanish and reopens the respawn's fresh
        generation (``reconnects``), so sweeping before respawn is
        safe."""
        if self.proto != "shm" or self.launch_info is None:
            return
        from blendjax.btt.shm_rpc import unlink_base

        for name, addrs in self.launch_info.addresses.items():
            addr = addrs[idx]
            if not addr.startswith("shm://"):
                continue
            removed = unlink_base(addr[len("shm://"):])
            if removed:
                logger.debug(
                    "swept %d shm objects of instance %d socket %s",
                    len(removed), idx, name,
                )

    # -- lifecycle ----------------------------------------------------------

    def __enter__(self):
        if self.launch_info is not None:
            raise RuntimeError("Already launched.")

        addresses = self._addresses()

        seed = self.seed
        if seed is None:
            seed = int(np.random.randint(np.iinfo(np.int32).max - self.num_instances))
        seeds = [seed + i for i in range(self.num_instances)]

        popen_kwargs = popen_group_kwargs()

        env = child_env()
        processes, commands = [], []
        try:
            for idx in range(self.num_instances):
                script_args = [
                    "-btid",
                    str(idx),
                    "-btseed",
                    str(seeds[idx]),
                    "-btsockets",
                    *[f"{name}={addrs[idx]}" for name, addrs in addresses.items()],
                    *self.instance_args[idx],
                ]
                cmd = [str(self.blender_info["path"])]
                if self.scene:
                    cmd.append(str(self.scene))
                if self.background:
                    cmd.append("--background")
                cmd += ["--python-use-system-env", "--python", str(self.script), "--"]
                cmd += script_args

                p = subprocess.Popen(cmd, shell=False, env=env, **popen_kwargs)
                processes.append(p)
                commands.append(list(cmd))
                logger.info("Started instance %d: %s", idx, " ".join(cmd))
        except Exception:
            for p in processes:
                self._stop_process(p)
            self._unlink_shm()
            raise

        self.launch_info = LaunchInfo(addresses, commands, processes=processes)
        return self

    def respawn(self, idx):
        """Respawn instance ``idx`` with its original command line (same
        addresses, same seed — shm ring names carry the launch nonce, so
        the reader's generation-reopen elasticity keeps working).  Used by
        :class:`blendjax.btt.watchdog.FleetWatchdog` restarts; callable
        directly for manual healing.  Returns the new process."""
        info = self.launch_info
        if info is None:
            raise RuntimeError("Not launched.")
        if info.processes[idx] is None:
            raise RuntimeError(
                f"instance {idx} is retired; a retired slot is never "
                "respawned"
            )
        # the dead incarnation ran no cleanup (SIGKILL): sweep its shm
        # objects BEFORE the respawn recreates them, or every crash
        # strands stale ring generations in /dev/shm
        self._unlink_instance_shm(idx)
        new = subprocess.Popen(
            info.commands[idx],
            shell=False,
            env=child_env(),
            **popen_group_kwargs(),
        )
        info.processes[idx] = new
        logger.info("Respawned instance %d as pid %d", idx, new.pid)
        return new

    def retire(self, idx):
        """Permanently retire instance ``idx`` (the autoscale
        scale-down surface): stop its process group and keep the index
        slot as ``None``, so fleet indices stay stable and a
        :class:`~blendjax.btt.watchdog.FleetWatchdog` skips the slot
        instead of respawning it.  Idempotent — retiring a retired
        slot returns ``False``."""
        info = self.launch_info
        if info is None:
            raise RuntimeError("Not launched.")
        p = info.processes[idx]
        if p is None:
            return False
        self._stop_process(p)
        info.processes[idx] = None
        self._unlink_instance_shm(idx)
        logger.info("Retired instance %d", idx)
        return True

    def assert_alive(self):
        """Raise if any launched process has exited (reference ``:166-171``)."""
        if self.launch_info is None:
            return
        codes = self._poll()
        if any(c is not None for c in codes):
            raise RuntimeError(f"Blender instance(s) died; exit codes {codes}")

    def wait(self):
        """Block until every launched process terminates."""
        for p in self.launch_info.processes:
            if p is not None:
                p.wait()

    def __exit__(self, exc_type, exc_value, exc_traceback):
        for p in self.launch_info.processes:
            if p is not None:
                self._stop_process(p)
        remaining = [p for p in self.launch_info.processes
                     if p is not None and p.poll() is None]
        self._unlink_shm()
        self.launch_info = None
        if remaining:
            raise RuntimeError("Not all Blender instances closed.")
        logger.info("Blender instances closed")
        return False

    def _stop_process(self, p):
        """terminate -> (grace) -> kill, addressed to the process group."""
        if p.poll() is not None:
            return
        try:
            if os.name == "posix" and os.getpgid(p.pid) != os.getpgrp():
                os.killpg(os.getpgid(p.pid), _signal.SIGTERM)
            else:
                p.terminate()
        except (ProcessLookupError, PermissionError):
            p.terminate()
        try:
            p.wait(timeout=self.shutdown_grace)
        except subprocess.TimeoutExpired:
            logger.warning("Instance pid=%d ignored SIGTERM; killing.", p.pid)
            try:
                if os.name == "posix" and os.getpgid(p.pid) != os.getpgrp():
                    os.killpg(os.getpgid(p.pid), _signal.SIGKILL)
                else:
                    p.kill()
            except (ProcessLookupError, PermissionError):
                p.kill()
            p.wait()

    def _poll(self):
        if self.launch_info is None or self.launch_info.processes is None:
            return []
        return [None if p is None else p.poll()
                for p in self.launch_info.processes]
