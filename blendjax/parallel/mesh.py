"""Device-mesh construction helpers.

The reference has no model-side parallelism at all (SURVEY.md §2.4); on TPU
the training side of every blendjax example scales through one of these
meshes + ``jax.jit`` with sharding annotations, letting XLA insert the
collectives over ICI.  Conventions:

- axis ``'data'``  — batch (DP) axis; streams are fed per-host shards.
- axis ``'model'`` — tensor-parallel axis for wide layers.

``make_mesh({'data': 4, 'model': 2})`` builds a 2-D mesh over the first 8
local/global devices.
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(axes: dict, devices=None) -> Mesh:
    """Build a mesh with the given ``{axis_name: size}`` layout.

    ``devices`` defaults to ``jax.devices()``; sizes must multiply to at
    most the device count (extras are left unused).
    """
    names = tuple(axes)
    sizes = tuple(axes.values())
    need = math.prod(sizes)
    devices = list(jax.devices()) if devices is None else list(devices)
    if need > len(devices):
        raise ValueError(f"mesh {axes} needs {need} devices, have {len(devices)}")
    arr = np.array(devices[:need]).reshape(sizes)
    return Mesh(arr, names)


def data_mesh(num_devices=None) -> Mesh:
    """1-D data-parallel mesh over all (or the first N) devices."""
    devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    return make_mesh({"data": len(devices)}, devices)


def data_sharding(mesh: Mesh, axis="data") -> NamedSharding:
    """Shard the leading (batch) dimension over ``axis``, replicate the rest."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
